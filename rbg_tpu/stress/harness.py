"""Stress/scale harness: control-plane latency percentiles under churn.

Reference analog: ``test/stress`` (inventory #28, SURVEY.md §4.4/§6 — the
reference's ONLY performance apparatus): create N groups at a configured
QPS against a kwok-style fake fleet, measure per-phase create→Ready /
update→Converged / delete→Gone latencies as P50/P90/P99, and capture
controller metrics. BASELINE.md maps "role-placement latency" onto exactly
these percentiles.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional

from rbg_tpu.api import constants as C
from rbg_tpu.api.meta import get_condition
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@dataclasses.dataclass
class StressConfig:
    groups: int = 10
    roles_per_group: int = 2
    replicas: int = 2
    create_qps: float = 5.0
    update: bool = True
    delete: bool = True
    slices: int = 64
    hosts_per_slice: int = 4
    timeout_per_group: float = 30.0
    # "fake" drives FakeKubelet in-process (kwok analog); "k8s" runs the
    # FULL K8s mirror backend against an in-repo fake apiserver over real
    # HTTP — every pod create/patch/delete is a REST round trip and status
    # comes back through the watch reflector (VERDICT r4 #4: the newest
    # backend needs scale evidence, not just CRUD tests).
    backend: str = "fake"


def _pcts(samples: List[float]) -> Dict[str, float]:
    if not samples:
        return {"p50": 0.0, "p90": 0.0, "p99": 0.0, "n": 0}
    s = sorted(samples)

    def pct(q):
        i = min(len(s) - 1, int(q * len(s)))
        return round(s[i] * 1000, 2)  # ms

    return {"p50": pct(0.50), "p90": pct(0.90), "p99": pct(0.99),
            "n": len(s), "max": round(s[-1] * 1000, 2)}


def run_stress(cfg: StressConfig, plane: Optional[ControlPlane] = None) -> dict:
    own_plane = plane is None
    apiserver = None
    if own_plane:
        if cfg.backend == "k8s":
            plane, apiserver = _k8s_plane(cfg)
        else:
            plane = ControlPlane(backend="fake")
            make_tpu_nodes(plane.store, slices=cfg.slices,
                           hosts_per_slice=cfg.hosts_per_slice)
        plane.start()
    REGISTRY.reset()
    try:
        report = _run(cfg, plane)
        report["backend"] = cfg.backend if own_plane else "caller"
        return report
    finally:
        if own_plane:
            plane.stop()
            if apiserver is not None:
                apiserver.stop()


def _k8s_plane(cfg: StressConfig):
    """A plane whose pods mirror to the in-repo fake apiserver (the kwok
    analog) over real HTTP, GKE-TPU-shaped nodes (node pool == slice)."""
    from rbg_tpu.k8s import translate as T
    from rbg_tpu.k8s.client import KubeClient
    from rbg_tpu.k8s.fake_apiserver import FakeK8sApiServer

    apiserver = FakeK8sApiServer()
    for s in range(cfg.slices):
        for h in range(cfg.hosts_per_slice):
            apiserver.add_node(
                f"slice-{s}-host-{h}",
                labels={
                    T.LABEL_GKE_TPU_ACCEL: "tpu-v5-lite-podslice",
                    T.LABEL_GKE_TPU_TOPOLOGY: "2x4",
                    T.LABEL_GKE_NODEPOOL: f"pool-{s}",
                    T.LABEL_WORKER_INDEX: str(h),
                    T.LABEL_HOSTNAME: f"slice-{s}-host-{h}",
                },
                address=f"10.{s // 250}.{s % 250}.{h + 10}",
                tpu=4,
            )
    apiserver.start()
    plane = ControlPlane(backend="k8s",
                         k8s_client=KubeClient(apiserver.url))
    return plane, apiserver


def _run(cfg: StressConfig, plane: ControlPlane) -> dict:
    interval = 1.0 / cfg.create_qps if cfg.create_qps > 0 else 0.0
    names = [f"stress-{i}" for i in range(cfg.groups)]

    def ready(name) -> bool:
        g = plane.store.get("RoleBasedGroup", "default", name)
        if g is None:
            return False
        c = get_condition(g.status.conditions, C.COND_READY)
        return c is not None and c.status == "True"

    # --- create phase ---
    # A background stack-sampling profiler runs through the phase and its
    # top sites land in the report (reference: test/stress/pprof.go scrapes
    # controller pprof into the HTML report).
    from rbg_tpu.obs.profiler import BackgroundProfiler

    # Ready transitions are observed by a WATCHER so each group's latency is
    # its own (polling after the create burst inflated early groups' numbers
    # by the remaining burst duration — the round-1 "3.1s p99" was mostly
    # this measurement artifact, not control-plane latency).
    t_created: Dict[str, float] = {}
    t_ready: Dict[str, float] = {}
    want = set(names)

    def on_group_event(ev):
        g = ev.object
        n = g.metadata.name
        if n in want and n not in t_ready and getattr(ev, "type", "") != "DELETED":
            c = get_condition(g.status.conditions, C.COND_READY)
            if c is not None and c.status == "True":
                t_ready[n] = time.perf_counter()

    plane.store.watch("RoleBasedGroup", on_group_event)

    with BackgroundProfiler() as create_prof:
        for i, name in enumerate(names):
            roles = [simple_role(f"role{j}", replicas=cfg.replicas)
                     for j in range(cfg.roles_per_group)]
            for j in range(1, len(roles)):
                roles[j].dependencies = [roles[0].name]
            t_created[name] = time.perf_counter()
            plane.apply(make_group(name, *roles))
            if interval:
                time.sleep(interval)
        for name in names:
            plane.wait_for(lambda n=name: n in t_ready or ready(n),
                           timeout=cfg.timeout_per_group, desc=f"{name} ready")
            t_ready.setdefault(name, time.perf_counter())  # watcher raced: now
    create_lat = [t_ready[n] - t_created[n] for n in names]

    # --- update phase (image-only → exercises the in-place engine) ---
    update_lat: List[float] = []
    if cfg.update:
        for name in names:
            g = plane.store.get("RoleBasedGroup", "default", name)
            for r in g.spec.roles:
                r.template.containers[0].image = "engine:v2"
            plane.store.update(g)
            t0 = time.perf_counter()

            def converged(n=name):
                pods = plane.store.list(
                    "Pod", namespace="default",
                    selector={C.LABEL_GROUP_NAME: n})
                return pods and all(
                    p.template.containers[0].image == "engine:v2" and p.running_ready
                    for p in pods if p.active
                ) and ready(n)

            plane.wait_for(converged, timeout=cfg.timeout_per_group,
                           desc=f"{name} updated")
            update_lat.append(time.perf_counter() - t0)

    # --- delete phase ---
    delete_lat: List[float] = []
    if cfg.delete:
        for name in names:
            plane.store.delete("RoleBasedGroup", "default", name)
            t0 = time.perf_counter()

            def gone(n=name):
                return not plane.store.list(
                    "Pod", namespace="default", selector={C.LABEL_GROUP_NAME: n})

            plane.wait_for(gone, timeout=cfg.timeout_per_group,
                           desc=f"{name} deleted")
            delete_lat.append(time.perf_counter() - t0)

    report = {
        "config": dataclasses.asdict(cfg),
        "create_to_ready_ms": _pcts(create_lat),
        "update_to_converged_ms": _pcts(update_lat),
        "delete_to_gone_ms": _pcts(delete_lat),
        "reconcile_p99_s": {
            c: REGISTRY.quantile("rbg_reconcile_duration_seconds", 0.99, controller=c)
            for c in ("rolebasedgroup", "roleinstanceset", "roleinstance", "scheduler")
        },
        "create_phase_profile": create_prof.result,
    }
    return report


# ---- serving-plane overload scenario ---------------------------------------


@dataclasses.dataclass
class OverloadConfig:
    """Sustained-overload drill against ONE in-process EngineService: more
    concurrent demand than the engine's batch + queue can hold, so the
    admission gates MUST shed. The report carries the robustness
    invariants the serving plane promises under overload."""

    clients: int = 6
    requests_per_client: int = 6
    max_queue: int = 4
    max_batch: int = 2
    max_new_tokens: int = 24
    prompt_len: int = 8
    timeout_s: float = 60.0        # per-request deadline budget
    model: str = "tiny"


def run_serving_overload(cfg: OverloadConfig, service=None) -> dict:
    """Fire ``clients`` threads of back-to-back generates at a deliberately
    undersized service and report what the overload machinery did:
    admitted-request latency percentiles, shed/deadline counts, and the
    max queue depth ever observed (the bounded-queue invariant)."""
    import threading

    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.service import (DeadlineExceeded, EngineService,
                                        Overloaded)

    own = service is None
    if own:
        service = EngineService(
            EngineConfig(model=cfg.model, page_size=8, num_pages=256,
                         max_batch=cfg.max_batch, max_seq_len=256,
                         prefill_chunk=16, use_pallas="never",
                         decode_buckets=(cfg.max_batch,)),
            max_queue=cfg.max_queue)
    outcomes = {"ok": 0, "overloaded": 0, "deadline_exceeded": 0, "error": 0}
    latencies: List[float] = []
    retry_hints: List[float] = []
    olock = threading.Lock()
    depth_max = [0]
    stop_probe = threading.Event()

    def probe_depth():
        while not stop_probe.is_set():
            with service._lock:
                d = len(service._queue)
            depth_max[0] = max(depth_max[0], d)
            time.sleep(0.002)

    def client(ci: int):
        sp = SamplingParams(max_new_tokens=cfg.max_new_tokens)
        prompt = [(ci * 17 + j) % 200 + 1 for j in range(cfg.prompt_len)]
        for _ in range(cfg.requests_per_client):
            t0 = time.monotonic()
            try:
                service.submit_wait(prompt, sp,
                                    deadline=t0 + cfg.timeout_s)
            except Overloaded as e:
                with olock:
                    outcomes["overloaded"] += 1
                    if e.retry_after_s is not None:
                        retry_hints.append(e.retry_after_s)
                continue
            except DeadlineExceeded:
                with olock:
                    outcomes["deadline_exceeded"] += 1
                continue
            except Exception:
                with olock:
                    outcomes["error"] += 1
                continue
            with olock:
                outcomes["ok"] += 1
                latencies.append(time.monotonic() - t0)

    prober = threading.Thread(target=probe_depth, daemon=True)
    prober.start()
    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(cfg.clients)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        stop_probe.set()
        prober.join()
        if own:
            service.stop()
    stats = service.service_stats()
    total = cfg.clients * cfg.requests_per_client
    report = {
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(time.perf_counter() - t_start, 3),
        "outcomes": outcomes,
        "admitted_latency_ms": _pcts(latencies),
        "retry_after_hint_s": (round(min(retry_hints), 3)
                               if retry_hints else None),
        "max_queue_depth_observed": depth_max[0],
        "service": stats,
        "invariants": {
            # The three promises the overload machinery makes:
            "queue_bounded": depth_max[0] <= cfg.max_queue,
            "all_accounted": sum(outcomes.values()) == total,
            "shed_instead_of_queued": (outcomes["overloaded"] == 0
                                       or stats["shed_total"] > 0),
        },
    }
    return report


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="rbg-tpu-stress")
    ap.add_argument("--scenario", default="churn",
                    choices=["churn", "overload"],
                    help="churn = control-plane create/update/delete "
                         "percentiles; overload = serving-plane admission "
                         "control drill (sheds, deadlines, queue bound)")
    ap.add_argument("--clients", type=int, default=6)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--max-queue", type=int, default=4)
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--timeout-s", type=float, default=60.0)
    ap.add_argument("--groups", type=int, default=10)
    ap.add_argument("--roles", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--qps", type=float, default=5.0)
    ap.add_argument("--slices", type=int, default=64)
    ap.add_argument("--hosts", type=int, default=4)
    ap.add_argument("--json", action="store_true", help="machine output only")
    ap.add_argument("--html", metavar="FILE", help="also write an HTML report")
    ap.add_argument("--backend", default="fake", choices=["fake", "k8s"],
                    help="fake = in-process FakeKubelet (kwok analog); "
                         "k8s = full mirror backend against the in-repo "
                         "fake apiserver over real HTTP")
    ap.add_argument("--json-out", metavar="FILE",
                    help="also write the JSON report to FILE (committed "
                         "per round like BENCH)")
    args = ap.parse_args(argv)
    import os
    load1 = os.getloadavg()[0]
    if args.scenario == "overload":
        report = run_serving_overload(OverloadConfig(
            clients=args.clients, requests_per_client=args.requests,
            max_queue=args.max_queue, max_batch=args.max_batch,
            timeout_s=args.timeout_s))
        report["load1_before"] = round(load1, 2)
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(report, f, indent=1)
        print(json.dumps(report) if args.json
              else json.dumps(report, indent=2))
        return 0
    cfg = StressConfig(groups=args.groups, roles_per_group=args.roles,
                       replicas=args.replicas, create_qps=args.qps,
                       slices=args.slices, hosts_per_slice=args.hosts,
                       backend=args.backend)
    report = run_stress(cfg)
    report["load1_before"] = round(load1, 2)
    report["command"] = "rbg-tpu stress " + " ".join(
        argv if argv is not None else __import__("sys").argv[1:])
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=1)
    if args.html:
        write_html_report(report, args.html)
    if args.json:
        print(json.dumps(report))
    else:
        print(json.dumps(report, indent=2))
    return 0


def write_html_report(report: dict, path: str) -> None:
    """HTML report (reference analog: test/stress report.go's HTML output)."""
    rows = []
    for phase in ("create_to_ready_ms", "update_to_converged_ms",
                  "delete_to_gone_ms"):
        p = report[phase]
        rows.append(
            f"<tr><td>{phase.replace('_', ' ')}</td>"
            f"<td>{p.get('p50', 0)}</td><td>{p.get('p90', 0)}</td>"
            f"<td>{p.get('p99', 0)}</td><td>{p.get('max', 0)}</td>"
            f"<td>{p.get('n', 0)}</td></tr>")
    rec = "".join(
        f"<tr><td>{c}</td><td>{v}</td></tr>"
        for c, v in (report.get("reconcile_p99_s") or {}).items())
    prof = report.get("create_phase_profile") or {}
    prof_rows = "".join(
        f"<tr><td>{t['site']}</td><td>{t['samples']}</td></tr>"
        for t in prof.get("top", [])[:15])
    html = f"""<!doctype html><html><head><meta charset="utf-8">
<title>rbg-tpu stress report</title>
<style>body{{font-family:sans-serif;margin:2rem}}table{{border-collapse:collapse}}
td,th{{border:1px solid #999;padding:4px 10px;text-align:right}}
th{{background:#eee}}td:first-child{{text-align:left}}</style></head><body>
<h1>rbg-tpu control-plane stress report</h1>
<p>config: {json.dumps(report.get("config", {}))}</p>
<table><tr><th>phase</th><th>p50 (ms)</th><th>p90</th><th>p99</th>
<th>max</th><th>n</th></tr>{"".join(rows)}</table>
<h2>reconcile p99 (s)</h2>
<table><tr><th>controller</th><th>p99</th></tr>{rec}</table>
<h2>create-phase CPU profile (top sample sites,
{prof.get("samples", 0)} samples)</h2>
<table><tr><th>site</th><th>samples</th></tr>{prof_rows}</table>
</body></html>"""
    with open(path, "w") as f:
        f.write(html)


if __name__ == "__main__":
    import sys
    sys.exit(main())
