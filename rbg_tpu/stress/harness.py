"""Stress/scale harness: control-plane latency percentiles under churn.

Reference analog: ``test/stress`` (inventory #28, SURVEY.md §4.4/§6 — the
reference's ONLY performance apparatus): create N groups at a configured
QPS against a kwok-style fake fleet, measure per-phase create→Ready /
update→Converged / delete→Gone latencies as P50/P90/P99, and capture
controller metrics. BASELINE.md maps "role-placement latency" onto exactly
these percentiles.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional

from rbg_tpu.api import constants as C
from rbg_tpu.api.errors import CODE_DEADLINE, CODE_OVERLOADED
from rbg_tpu.api.meta import get_condition
from rbg_tpu.obs import names as metric_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@dataclasses.dataclass
class StressConfig:
    groups: int = 10
    roles_per_group: int = 2
    replicas: int = 2
    create_qps: float = 5.0
    update: bool = True
    delete: bool = True
    slices: int = 64
    hosts_per_slice: int = 4
    timeout_per_group: float = 30.0
    # "fake" drives FakeKubelet in-process (kwok analog); "k8s" runs the
    # FULL K8s mirror backend against an in-repo fake apiserver over real
    # HTTP — every pod create/patch/delete is a REST round trip and status
    # comes back through the watch reflector (VERDICT r4 #4: the newest
    # backend needs scale evidence, not just CRUD tests).
    backend: str = "fake"


def _pcts(samples: List[float]) -> Dict[str, float]:
    if not samples:
        return {"p50": 0.0, "p90": 0.0, "p99": 0.0, "n": 0}
    s = sorted(samples)

    def pct(q):
        i = min(len(s) - 1, int(q * len(s)))
        return round(s[i] * 1000, 2)  # ms

    return {"p50": pct(0.50), "p90": pct(0.90), "p99": pct(0.99),
            "n": len(s), "max": round(s[-1] * 1000, 2)}


def run_stress(cfg: StressConfig, plane: Optional[ControlPlane] = None) -> dict:
    own_plane = plane is None
    apiserver = None
    if own_plane:
        if cfg.backend == "k8s":
            plane, apiserver = _k8s_plane(cfg)
        else:
            plane = ControlPlane(backend="fake")
            make_tpu_nodes(plane.store, slices=cfg.slices,
                           hosts_per_slice=cfg.hosts_per_slice)
        plane.start()
    REGISTRY.reset()
    try:
        report = _run(cfg, plane)
        report["backend"] = cfg.backend if own_plane else "caller"
        return report
    finally:
        if own_plane:
            plane.stop()
            if apiserver is not None:
                apiserver.stop()


def _k8s_plane(cfg: StressConfig):
    """A plane whose pods mirror to the in-repo fake apiserver (the kwok
    analog) over real HTTP, GKE-TPU-shaped nodes (node pool == slice)."""
    from rbg_tpu.k8s import translate as T
    from rbg_tpu.k8s.client import KubeClient
    from rbg_tpu.k8s.fake_apiserver import FakeK8sApiServer

    apiserver = FakeK8sApiServer()
    for s in range(cfg.slices):
        for h in range(cfg.hosts_per_slice):
            apiserver.add_node(
                f"slice-{s}-host-{h}",
                labels={
                    T.LABEL_GKE_TPU_ACCEL: "tpu-v5-lite-podslice",
                    T.LABEL_GKE_TPU_TOPOLOGY: "2x4",
                    T.LABEL_GKE_NODEPOOL: f"pool-{s}",
                    T.LABEL_WORKER_INDEX: str(h),
                    T.LABEL_HOSTNAME: f"slice-{s}-host-{h}",
                },
                address=f"10.{s // 250}.{s % 250}.{h + 10}",
                tpu=4,
            )
    apiserver.start()
    plane = ControlPlane(backend="k8s",
                         k8s_client=KubeClient(apiserver.url))
    return plane, apiserver


def _run(cfg: StressConfig, plane: ControlPlane) -> dict:
    interval = 1.0 / cfg.create_qps if cfg.create_qps > 0 else 0.0
    names = [f"stress-{i}" for i in range(cfg.groups)]

    def ready(name) -> bool:
        g = plane.store.get("RoleBasedGroup", "default", name)
        if g is None:
            return False
        c = get_condition(g.status.conditions, C.COND_READY)
        return c is not None and c.status == "True"

    # --- create phase ---
    # A background stack-sampling profiler runs through the phase and its
    # top sites land in the report (reference: test/stress/pprof.go scrapes
    # controller pprof into the HTML report).
    from rbg_tpu.obs.profiler import BackgroundProfiler

    # Ready transitions are observed by a WATCHER so each group's latency is
    # its own (polling after the create burst inflated early groups' numbers
    # by the remaining burst duration — the round-1 "3.1s p99" was mostly
    # this measurement artifact, not control-plane latency).
    t_created: Dict[str, float] = {}
    t_ready: Dict[str, float] = {}
    want = set(names)

    def on_group_event(ev):
        g = ev.object
        n = g.metadata.name
        if n in want and n not in t_ready and getattr(ev, "type", "") != "DELETED":
            c = get_condition(g.status.conditions, C.COND_READY)
            if c is not None and c.status == "True":
                t_ready[n] = time.perf_counter()

    plane.store.watch("RoleBasedGroup", on_group_event)

    with BackgroundProfiler() as create_prof:
        for i, name in enumerate(names):
            roles = [simple_role(f"role{j}", replicas=cfg.replicas)
                     for j in range(cfg.roles_per_group)]
            for j in range(1, len(roles)):
                roles[j].dependencies = [roles[0].name]
            t_created[name] = time.perf_counter()
            plane.apply(make_group(name, *roles))
            if interval:
                time.sleep(interval)
        for name in names:
            plane.wait_for(lambda n=name: n in t_ready or ready(n),
                           timeout=cfg.timeout_per_group, desc=f"{name} ready")
            t_ready.setdefault(name, time.perf_counter())  # watcher raced: now
    create_lat = [t_ready[n] - t_created[n] for n in names]

    # --- update phase (image-only → exercises the in-place engine) ---
    update_lat: List[float] = []
    if cfg.update:
        for name in names:
            g = plane.store.get("RoleBasedGroup", "default", name)
            for r in g.spec.roles:
                r.template.containers[0].image = "engine:v2"
            plane.store.update(g)
            t0 = time.perf_counter()

            def converged(n=name):
                pods = plane.store.list(
                    "Pod", namespace="default",
                    selector={C.LABEL_GROUP_NAME: n})
                return pods and all(
                    p.template.containers[0].image == "engine:v2" and p.running_ready
                    for p in pods if p.active
                ) and ready(n)

            plane.wait_for(converged, timeout=cfg.timeout_per_group,
                           desc=f"{name} updated")
            update_lat.append(time.perf_counter() - t0)

    # --- delete phase ---
    delete_lat: List[float] = []
    if cfg.delete:
        for name in names:
            plane.store.delete("RoleBasedGroup", "default", name)
            t0 = time.perf_counter()

            def gone(n=name):
                return not plane.store.list(
                    "Pod", namespace="default", selector={C.LABEL_GROUP_NAME: n})

            plane.wait_for(gone, timeout=cfg.timeout_per_group,
                           desc=f"{name} deleted")
            delete_lat.append(time.perf_counter() - t0)

    report = {
        "scenario": "churn",
        "config": dataclasses.asdict(cfg),
        "create_to_ready_ms": _pcts(create_lat),
        "update_to_converged_ms": _pcts(update_lat),
        "delete_to_gone_ms": _pcts(delete_lat),
        "reconcile_p99_s": {
            c: REGISTRY.quantile(metric_names.RECONCILE_DURATION_SECONDS, 0.99, controller=c)
            for c in ("rolebasedgroup", "roleinstanceset", "roleinstance", "scheduler")
        },
        "create_phase_profile": create_prof.result,
        # Flamegraph-folded full stacks (`root;caller;leaf N`), directly
        # consumable by flamegraph.pl / speedscope — the leaf-only `top`
        # table above can't tell WHICH caller chain owns a hot leaf.
        "profile_folded": (create_prof.result or {}).get("folded", []),
    }
    return report


# ---- 10k-node fleet control-plane scenario ---------------------------------


@dataclasses.dataclass
class FleetConfig:
    """Control-plane scale drill: O(1k–10k) simulated nodes and a group
    churn wave (create → image update → delete) against a live plane,
    publishing the per-controller reconcile-latency and scheduler-
    throughput curves the future watch/informer refactor will be judged
    against. Invariants:

    * ``workqueue_drained`` — after churn stops, every controller
      workqueue reaches empty (no self-sustaining reconcile storm);
    * ``no_stuck_keys`` — no key is parked in failure backoff at or past
      the stuck threshold when the drill ends;
    * ``reconcile_p99_bound`` — every controller's reconcile p99 stays
      under the bound;
    * ``events_accounted`` — the structured event recorder accounts for
      every recorded occurrence (live counts + evictions == recorded).
    """

    nodes: int = 5000
    hosts_per_slice: int = 4
    groups: int = 150
    roles_per_group: int = 2
    replicas: int = 2
    create_qps: float = 100.0
    update_fraction: float = 0.25    # groups image-updated mid-run
    delete_fraction: float = 0.25    # groups deleted mid-run (from the end)
    reconcile_p99_bound_s: float = 2.5
    stuck_failures_threshold: int = 5
    drain_timeout_s: float = 90.0
    timeout_s: float = 300.0
    sample_interval_s: float = 0.5   # throughput-curve sampling period
    # Head-sampling rate for the reconcile traces the exemplars link to
    # (the drill arms tracing itself; 1.0 would trace every reconcile of
    # a 10k-pod run — the sink only keeps the slowest anyway).
    trace_sample: float = 0.05
    # Event-plane throughput reps: after the main drill, run ``ab_reps``
    # fresh-plane repetitions of a lighter churn wave and gate on the
    # event-mode invariants — every rep completes, dedup is ENGAGED
    # (deduped > 0: the watch-carried plane is actually doing the
    # dedup work), and the rep-to-rep binds/s spread stays inside the
    # trimmed gate. (The PR-12 legacy arm is deleted — these gates are
    # what remains of the A/B now that the baseline has served its
    # purpose.) 0 = skip.
    ab_reps: int = 0
    ab_groups: int = 40
    ab_spread_max: float = 0.45
    ab_attempts: int = 2


FLEET_PERCENTILES = (0.50, 0.90, 0.95, 0.99)


def _reconciles_total(controller_names) -> float:
    return sum(
        REGISTRY.counter(metric_names.RECONCILE_TOTAL, controller=c,
                         result=r)
        for c in controller_names for r in ("success", "error"))


def _fleet_curve_sampler(plane, stop, out: List[dict], interval_s: float):
    """Background sampler turning cumulative counters into the drill's
    throughput curve: scheduler binds/s, reconciles/s, events/s, and the
    summed workqueue depth, per tick. Controller names come from the
    LIVE plane registration, never a parallel hard-coded list — a newly
    registered controller must not be invisible to the curve."""
    t0 = time.perf_counter()
    names = [c.name for c in plane.manager.controllers]

    def totals():
        ev = sum(REGISTRY.counter(metric_names.EVENTS_RECORDED_TOTAL, type=t)
                 for t in ("Normal", "Warning"))
        return (REGISTRY.counter(metric_names.SCHED_BINDS_TOTAL),
                _reconciles_total(names), ev)

    prev_t, prev = 0.0, totals()
    while not stop.wait(interval_s):
        now = time.perf_counter() - t0
        cur = totals()
        dt = max(1e-6, now - prev_t)
        out.append({
            "t": round(now, 3),
            "binds_per_s": round((cur[0] - prev[0]) / dt, 2),
            "reconciles_per_s": round((cur[1] - prev[1]) / dt, 2),
            "events_per_s": round((cur[2] - prev[2]) / dt, 2),
            "queue_depth": sum(len(c.queue)
                               for c in plane.manager.controllers),
        })
        prev_t, prev = now, cur


def _trimmed_spread(runs: List[float]) -> float:
    """(max-min)/median after dropping one min and one max when n ≥ 4
    (the bench.py estimator): one bimodal-throughput outlier must not
    flunk an otherwise clean A/B."""
    if len(runs) < 2:
        return 0.0
    s = sorted(runs)
    if len(s) >= 4:
        s = s[1:-1]
    mid = s[len(s) // 2]
    return (s[-1] - s[0]) / mid if mid else 0.0


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    return s[len(s) // 2] if s else 0.0


def _run_fleet_rep(cfg: FleetConfig) -> dict:
    """One throughput repetition: fresh plane over a fresh fleet, a
    create → image-update → delete churn wave, measured as (pooled
    reconcile p99, scheduler binds/s over the bind window) plus the
    event-plane dedup accounting."""
    import math

    slices = max(1, math.ceil(cfg.nodes / cfg.hosts_per_slice))
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=slices,
                   hosts_per_slice=cfg.hosts_per_slice)
    REGISTRY.reset()
    names = [f"ab-{i}" for i in range(cfg.ab_groups)]
    ok = True

    def ready(name) -> bool:
        g = plane.store.get("RoleBasedGroup", "default", name, copy_=False)
        if g is None:
            return False
        c = get_condition(g.status.conditions, C.COND_READY)
        return c is not None and c.status == "True"

    def group_pods(name):
        return plane.store.list("Pod", namespace="default",
                                selector={C.LABEL_GROUP_NAME: name},
                                copy_=False)

    # Exact reconcile durations (list.append is GIL-atomic): the
    # registry histogram's bucket-quantized p99 cannot arbitrate an A/B
    # where both variants land inside one bucket.
    from rbg_tpu.runtime.controller import Controller
    samples: List[tuple] = []
    Controller.reconcile_duration_hook = (
        lambda name, d: samples.append((name, d)))
    t0 = time.perf_counter()
    ready_s = 0.0
    try:
        # Inside the try: a start() failure must still stop the plane's
        # threads and uninstall the process-global duration hook, or the
        # leaked plane corrupts every later rep's measurements.
        plane.start()
        for name in names:
            roles = [simple_role(f"role{j}", replicas=cfg.replicas)
                     for j in range(cfg.roles_per_group)]
            plane.apply(make_group(name, *roles))
        for name in names:
            plane.wait_for(lambda n=name: ready(n), timeout=cfg.timeout_s,
                           desc=f"ab {name} ready")
        ready_s = time.perf_counter() - t0
        # Update wave on half the groups: status churn is where
        # self-write dedup earns its keep.
        upd = names[:max(1, len(names) // 2)]
        for name in upd:
            g = plane.store.get("RoleBasedGroup", "default", name)
            for r in g.spec.roles:
                r.template.containers[0].image = "engine:v2"
            plane.store.update(g)
        for name in upd:
            def converged(n=name):
                pods = group_pods(n)
                return pods and all(
                    p.template.containers[0].image == "engine:v2"
                    and p.running_ready for p in pods if p.active
                ) and ready(n)
            plane.wait_for(converged, timeout=cfg.timeout_s,
                           desc=f"ab {name} updated")
        for name in names:
            plane.store.delete("RoleBasedGroup", "default", name)
        for name in names:
            plane.wait_for(lambda n=name: not group_pods(n),
                           timeout=cfg.timeout_s, desc=f"ab {name} gone")
    except TimeoutError:
        ok = False
    finally:
        try:
            plane.stop()
        finally:
            Controller.reconcile_duration_hook = None
    elapsed = time.perf_counter() - t0

    ctrl_names = [c.name for c in plane.manager.controllers]

    def _p99(vals: List[float]) -> float:
        s = sorted(vals)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    by_ctrl: Dict[str, List[float]] = {}
    for cname, d in samples:
        by_ctrl.setdefault(cname, []).append(d)
    p99s = {c: _p99(v) * 1000 for c, v in by_ctrl.items()}
    binds = REGISTRY.counter(metric_names.SCHED_BINDS_TOTAL)
    reconciles = _reconciles_total(ctrl_names)
    deduped = sum(
        REGISTRY.counter(metric_names.RECONCILE_DEDUPED_TOTAL, controller=c)
        for c in ctrl_names)
    return {
        "ok": ok,
        "elapsed_s": round(elapsed, 3),
        "ready_s": round(ready_s, 3),
        # EXACT p99 pooled across every controller's reconciles (the
        # registry histogram's bucket-quantized quantiles cannot carry a
        # per-rep tail comparison).
        "reconcile_p99_ms": round(
            _p99([d for _, d in samples]) * 1000, 3) if samples else 0.0,
        "reconcile_p99_worst_ms": round(max(p99s.values(), default=0.0), 3),
        "reconcile_p99_by_controller_ms":
            {c: round(v, 3) for c, v in p99s.items()},
        "binds_total": binds,
        "binds_per_s": round(binds / ready_s, 2) if ready_s else 0.0,
        "reconciles_total": reconciles,
        "deduped_total": deduped,
        "scan_p99_ms": round((REGISTRY.quantile(
            metric_names.SCHED_FEASIBILITY_SCAN_SECONDS, 0.99) or 0.0)
            * 1000, 3),
        "shard_skips_total": REGISTRY.counter(
            metric_names.SCHED_SHARD_SKIPS_TOTAL),
    }


def _run_fleet_reps(cfg: FleetConfig) -> dict:
    """Event-plane throughput repetitions with the trimmed-spread gate:
    every rep must complete, dedup must be ENGAGED (deduped > 0 — the
    watch-carried plane actually absorbing coalesced/stale triggers),
    and the rep-to-rep binds/s spread must stay inside the gate.
    Retries the whole block once (ab_attempts) before reporting a red —
    this box's bimodal throughput can sink a single attempt."""
    last = None
    for attempt in range(1, max(1, cfg.ab_attempts) + 1):
        reps: Dict[str, List[dict]] = {
            "event": [_run_fleet_rep(cfg) for _ in range(cfg.ab_reps)]}
        out: Dict[str, object] = {"attempt": attempt, "reps": reps}
        reps_ok = all(r["ok"] for r in reps["event"])
        med = {"event": {
            "reconcile_p99_ms": _median(
                [r["reconcile_p99_ms"] for r in reps["event"]]),
            "binds_per_s": _median(
                [r["binds_per_s"] for r in reps["event"]]),
            "scan_p99_ms": _median(
                [r["scan_p99_ms"] for r in reps["event"]]),
            "deduped_total": _median(
                [float(r["deduped_total"]) for r in reps["event"]]),
        }}
        spread = _trimmed_spread(
            [r["binds_per_s"] for r in reps["event"]])
        out.update({
            "median": med,
            "spread": round(spread, 4),
            "spread_max": cfg.ab_spread_max,
            "spread_estimator": "trimmed_minmax_drop1",
            "reps_ok": reps_ok,
            "dedup_engaged": med["event"]["deduped_total"] > 0,
            "spread_ok": spread <= cfg.ab_spread_max,
        })
        last = out
        if reps_ok and out["dedup_engaged"] and out["spread_ok"]:
            return out
    return last


def run_fleet(cfg: FleetConfig) -> dict:
    import math
    import threading

    from rbg_tpu.obs import trace

    slices = max(1, math.ceil(cfg.nodes / cfg.hosts_per_slice))
    plane = ControlPlane(backend="fake")
    # Nodes land BEFORE controllers start (no watchers yet): node
    # bring-up is fleet bootstrap, not the churn under measurement.
    make_tpu_nodes(plane.store, slices=slices,
                   hosts_per_slice=cfg.hosts_per_slice)
    n_nodes = slices * cfg.hosts_per_slice
    REGISTRY.reset()
    # Arm tracing for this run so reconcile-duration exemplars name the
    # slowest reconcile per controller (restored on exit).
    was_enabled, old_sample = trace.enabled(), trace._CFG.sample
    trace.configure(enabled=True, sample=cfg.trace_sample)
    trace.SINK.reset()

    ctrl_names = [c.name for c in plane.manager.controllers]
    names = [f"fleet-{i}" for i in range(cfg.groups)]
    n_update = int(cfg.groups * cfg.update_fraction)
    n_delete = int(cfg.groups * cfg.delete_fraction)
    deleted = set(names[cfg.groups - n_delete:]) if n_delete else set()
    curve: List[dict] = []
    stop_sampler = threading.Event()
    inv: Dict[str, bool] = {}
    phases: Dict[str, object] = {}
    pods_peak = 0
    t_run = time.perf_counter()

    def ready(name) -> bool:
        g = plane.store.get("RoleBasedGroup", "default", name, copy_=False)
        if g is None:
            return False
        c = get_condition(g.status.conditions, C.COND_READY)
        return c is not None and c.status == "True"

    plane.start()
    sampler = threading.Thread(
        target=_fleet_curve_sampler,
        args=(plane, stop_sampler, curve, cfg.sample_interval_s),
        daemon=True)
    sampler.start()
    try:
        # --- create wave ---
        interval = 1.0 / cfg.create_qps if cfg.create_qps > 0 else 0.0
        t0 = time.perf_counter()
        for name in names:
            roles = [simple_role(f"role{j}", replicas=cfg.replicas)
                     for j in range(cfg.roles_per_group)]
            plane.apply(make_group(name, *roles))
            if interval:
                time.sleep(interval)
        phases["create_s"] = round(time.perf_counter() - t0, 3)
        for name in names:
            plane.wait_for(lambda n=name: ready(n), timeout=cfg.timeout_s,
                           desc=f"{name} ready")
        phases["all_ready_s"] = round(time.perf_counter() - t0, 3)
        inv["all_groups_ready"] = True

        def group_pods(name):
            return plane.store.list("Pod", namespace="default",
                                    selector={C.LABEL_GROUP_NAME: name},
                                    copy_=False)

        pods_peak = max(pods_peak,
                        sum(len(group_pods(n)) for n in names))

        # --- churn wave: image update on a slice of the fleet ---
        t0 = time.perf_counter()
        for name in names[:n_update]:
            g = plane.store.get("RoleBasedGroup", "default", name)
            for r in g.spec.roles:
                r.template.containers[0].image = "engine:v2"
            plane.store.update(g)
        for name in names[:n_update]:
            def converged(n=name):
                pods = group_pods(n)
                return pods and all(
                    p.template.containers[0].image == "engine:v2"
                    and p.running_ready for p in pods if p.active
                ) and ready(n)
            plane.wait_for(converged, timeout=cfg.timeout_s,
                           desc=f"{name} updated")
        phases["update_s"] = round(time.perf_counter() - t0, 3)

        # --- churn wave: deletes ---
        t0 = time.perf_counter()
        for name in deleted:
            plane.store.delete("RoleBasedGroup", "default", name)
        for name in deleted:
            plane.wait_for(lambda n=name: not group_pods(n),
                           timeout=cfg.timeout_s, desc=f"{name} gone")
        phases["delete_s"] = round(time.perf_counter() - t0, 3)

        # --- drain: every workqueue must reach empty and STAY there ---
        t0 = time.perf_counter()

        def reconciles_now() -> float:
            return _reconciles_total(ctrl_names)

        def drained() -> bool:
            return sum(len(c.queue)
                       for c in plane.manager.controllers) == 0

        # "Drained" = ready queues empty AND no reconcile ran for a full
        # stability window. len(queue) alone counts only READY items — a
        # key ping-ponging through requeue_after/backoff delays would
        # read as an empty queue at nearly every poll while the plane
        # churns forever; the reconcile-counter delta catches it.
        stable_since = [None]
        stable_base = [0.0]

        def drained_stable() -> bool:
            if not drained():
                stable_since[0] = None
                return False
            total = reconciles_now()
            if stable_since[0] is None or total != stable_base[0]:
                stable_since[0] = time.monotonic()
                stable_base[0] = total
                return False
            return time.monotonic() - stable_since[0] >= 1.0

        try:
            plane.wait_for(drained_stable, timeout=cfg.drain_timeout_s,
                           interval=0.05, desc="workqueues drained")
            inv["workqueue_drained"] = True
        except TimeoutError:
            inv["workqueue_drained"] = False
        phases["drain_s"] = round(time.perf_counter() - t0, 3)

        controller_stats = [c.stats() for c in plane.manager.controllers]
    except TimeoutError as e:
        inv.setdefault("all_groups_ready", False)
        inv.setdefault("workqueue_drained", False)
        controller_stats = [c.stats() for c in plane.manager.controllers]
        # pods_peak keeps whatever was measured before the timeout — a
        # create-then-update-timeout report must not claim zero pods.
        phases["timeout"] = str(e)
    finally:
        stop_sampler.set()
        sampler.join(timeout=5.0)
        plane.stop()
        trace.configure(enabled=was_enabled, sample=old_sample)

    # --- per-controller reconcile-latency percentile curves ---
    latency: Dict[str, dict] = {}
    for c in ctrl_names:
        st = REGISTRY.hist_stats(metric_names.RECONCILE_DURATION_SECONDS,
                                 controller=c)
        if not st or not st["count"]:
            continue
        pts = [
            {"pct": int(p * 100),
             "ms": round((REGISTRY.quantile(
                 metric_names.RECONCILE_DURATION_SECONDS, p,
                 controller=c) or 0.0) * 1000, 3)}
            for p in FLEET_PERCENTILES]
        qa = REGISTRY.quantile(metric_names.WORKQUEUE_QUEUE_AGE_SECONDS,
                               0.99, controller=c)
        latency[c] = {
            "n": st["count"], "max_ms": round(st["max"] * 1000, 3),
            "curve": pts,
            "queue_age_p99_ms": (round(qa * 1000, 3)
                                 if qa is not None else None),
        }
    inv["reconcile_latency_curves"] = bool(latency)
    inv["reconcile_p99_bound"] = all(
        next(p["ms"] for p in v["curve"] if p["pct"] == 99) / 1000.0
        <= cfg.reconcile_p99_bound_s for v in latency.values()
    ) if latency else False

    # --- stuck keys ---
    stuck = [
        {"controller": st["name"], **sk}
        for st in controller_stats for sk in st["stuck_keys"]
        if sk["failures"] >= cfg.stuck_failures_threshold]
    inv["no_stuck_keys"] = not stuck

    # --- event-plane accounting (registry was reset at drill start) ---
    ev_stats = plane.store.event_stats()
    recorded = sum(REGISTRY.counter(metric_names.EVENTS_RECORDED_TOTAL,
                                    type=t) for t in ("Normal", "Warning"))
    evicted = REGISTRY.counter(metric_names.EVENTS_EVICTED_TOTAL)
    inv["events_accounted"] = (recorded
                               == ev_stats["total_count"] + evicted)

    # --- scheduler throughput + feasibility scans ---
    scan = REGISTRY.hist_stats(
        metric_names.SCHED_FEASIBILITY_SCAN_SECONDS) or {}
    sched = {
        "binds_total": REGISTRY.counter(metric_names.SCHED_BINDS_TOTAL),
        "peak_binds_per_s": max((c["binds_per_s"] for c in curve),
                                default=0.0),
        "feasibility_scans": scan.get("count", 0),
        "scan_p50_ms": round((REGISTRY.quantile(
            metric_names.SCHED_FEASIBILITY_SCAN_SECONDS, 0.5) or 0.0)
            * 1000, 3),
        "scan_p99_ms": round((REGISTRY.quantile(
            metric_names.SCHED_FEASIBILITY_SCAN_SECONDS, 0.99) or 0.0)
            * 1000, 3),
    }
    inv["scheduler_throughput_curve"] = any(
        c["binds_per_s"] > 0 for c in curve)

    # --- slowest reconcile per controller (exemplar → waterfall) ---
    slowest_by_controller = {}
    for c in ctrl_names:
        ex = REGISTRY.exemplars(metric_names.RECONCILE_DURATION_SECONDS,
                                controller=c)
        if not ex:
            continue
        worst = max(ex.values(), key=lambda e: e["value"])
        slowest_by_controller[c] = {
            "duration_ms": round(worst["value"] * 1000, 3),
            "trace_id": worst["trace_id"]}
    from rbg_tpu.obs import trace as _trace
    slow_recs = [r for r in _trace.SINK.slowest(16)
                 if r["root"].startswith("controller.")]
    waterfall = _trace.waterfall(slow_recs[0]) if slow_recs else []

    # --- event-carried dedup accounting for the MAIN drill (read before
    # the A/B reps reset the registry) ---
    dedup = {
        "reconcile_deduped_total": sum(
            REGISTRY.counter(metric_names.RECONCILE_DEDUPED_TOTAL,
                             controller=c) for c in ctrl_names),
        "backstop_enqueued_total": sum(
            REGISTRY.counter(metric_names.RESYNC_BACKSTOP_ENQUEUED_TOTAL,
                             controller=c) for c in ctrl_names),
        "backstop_skipped_total": sum(
            REGISTRY.counter(metric_names.RESYNC_BACKSTOP_SKIPPED_TOTAL,
                             controller=c) for c in ctrl_names),
        "shard_scans_total": REGISTRY.counter(
            metric_names.SCHED_SHARD_SCANS_TOTAL),
        "shard_skips_total": REGISTRY.counter(
            metric_names.SCHED_SHARD_SKIPS_TOTAL),
    }
    events_deduped_total = REGISTRY.counter(
        metric_names.EVENTS_DEDUPED_TOTAL)

    # --- event-plane throughput reps (resets the registry per rep —
    # every main-drill metric above is already materialized) ---
    ab = None
    if cfg.ab_reps > 0:
        ab = _run_fleet_reps(cfg)
        inv["ab_reps_ok"] = bool(ab["reps_ok"])
        inv["ab_dedup_engaged"] = bool(ab["dedup_engaged"])
        inv["ab_spread_ok"] = bool(ab["spread_ok"])

    return {
        "scenario": "fleet",
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(time.perf_counter() - t_run, 3),
        "fleet": {"nodes": n_nodes, "slices": slices,
                  "groups": cfg.groups, "pods_peak": pods_peak,
                  "updated": n_update, "deleted": n_delete},
        "phases": phases,
        "reconcile_latency": latency,
        "scheduler": sched,
        "throughput_curve": curve,
        "workqueues": controller_stats,
        "stuck_keys": stuck,
        "events": {**ev_stats, "recorded_total": recorded,
                   "deduped_total": events_deduped_total,
                   "evicted_total": evicted},
        "dedup": dedup,
        "event_reps": ab,
        "slowest_reconcile_by_controller": slowest_by_controller,
        "slowest_reconcile_waterfall": waterfall,
        "invariants": inv,
    }


# ---- serving-plane overload scenario ---------------------------------------


@dataclasses.dataclass
class OverloadConfig:
    """Sustained-overload drill against ONE in-process EngineService: more
    concurrent demand than the engine's batch + queue can hold, so the
    admission gates MUST shed. The report carries the robustness
    invariants the serving plane promises under overload."""

    clients: int = 6
    requests_per_client: int = 6
    max_queue: int = 4
    max_batch: int = 2
    max_new_tokens: int = 24
    prompt_len: int = 8
    timeout_s: float = 60.0        # per-request deadline budget
    model: str = "tiny"
    # Mixed trace (continuous batching): per-request prompt lengths cycle
    # through this tuple, so the engine serves prefill-heavy and
    # decode-heavy rows TOGETHER and the continuous-admission invariant
    # (no admitted request waits more than one step beyond page/slot
    # availability) is actually exercised. Empty tuple = fixed prompt_len.
    # A caller who customizes prompt_len while leaving this at its default
    # gets fixed-length prompts (see __post_init__) — prompt_len predates
    # the trace and must not be silently ignored.
    mixed_prompt_lens: tuple = (4, 12, 24, 40)
    # SLO targets the drill's service judges finished requests against
    # (obs/slo.py). Generous for a CPU-proxy tiny engine under deliberate
    # overload: the interesting output is the goodput-vs-throughput gap
    # plus the slo_accounted invariant, not a red/green pass bar.
    slo_ttft_s: float = 10.0
    slo_tpot_s: float = 1.0

    def __post_init__(self):
        fields = type(self).__dataclass_fields__
        if (self.prompt_len != fields["prompt_len"].default
                and self.mixed_prompt_lens
                == fields["mixed_prompt_lens"].default):
            self.mixed_prompt_lens = ()


def run_serving_overload(cfg: OverloadConfig, service=None) -> dict:
    """Fire ``clients`` threads of back-to-back generates at a deliberately
    undersized service and report what the overload machinery did:
    admitted-request latency percentiles, shed/deadline counts, and the
    max queue depth ever observed (the bounded-queue invariant)."""
    import threading

    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.service import (DeadlineExceeded, EngineService,
                                        Overloaded)

    from rbg_tpu.obs import timeseries

    own = service is None
    if own:
        service = EngineService(
            EngineConfig(model=cfg.model, page_size=8, num_pages=256,
                         max_batch=cfg.max_batch, max_seq_len=256,
                         prefill_chunk=16, use_pallas="never",
                         decode_buckets=(cfg.max_batch,),
                         slo_ttft_s=cfg.slo_ttft_s,
                         slo_tpot_s=cfg.slo_tpot_s),
            max_queue=cfg.max_queue)
        from rbg_tpu.utils import jitwatch
        if jitwatch.enabled():
            # The compile sentry needs a warmed service: warmup records
            # the blessed compile set, then warmup_complete() (called at
            # its end) arms the gate — every compile the overload itself
            # triggers is a zero_unwarmed_compiles red.
            service.warmup(input_len=32, out_len=2)
    # Windowed-signal plane: sample through the drill so the report's
    # signals section reflects THIS run's windows.
    sampler = timeseries.ensure_started()
    totals_before = service.slo.totals()
    outcomes = {"ok": 0, CODE_OVERLOADED: 0, CODE_DEADLINE: 0, "error": 0}
    latencies: List[float] = []
    retry_hints: List[float] = []
    olock = threading.Lock()
    depth_max = [0]
    stop_probe = threading.Event()

    def probe_depth():
        while not stop_probe.is_set():
            with service._lock:
                d = len(service._queue)
            depth_max[0] = max(depth_max[0], d)
            time.sleep(0.002)

    def client(ci: int):
        from rbg_tpu.obs import trace
        sp = SamplingParams(max_new_tokens=cfg.max_new_tokens)
        for ri in range(cfg.requests_per_client):
            plen = (cfg.mixed_prompt_lens[(ci + ri)
                                          % len(cfg.mixed_prompt_lens)]
                    if cfg.mixed_prompt_lens else cfg.prompt_len)
            prompt = [(ci * 17 + ri * 5 + j) % 200 + 1 for j in range(plen)]
            t0 = time.monotonic()
            # Root span per drill request (sampling per --trace-sample);
            # the service's queue-wait/scan spans — and the shed/deadline
            # rejections — parent under it, so the report's waterfall is
            # the real hop timeline, not a synthetic one.
            root = trace.start_trace(metric_names.SPAN_STRESS_REQUEST,
                                     client=ci, request=ri)
            try:
                service.submit_wait(prompt, sp,
                                    deadline=t0 + cfg.timeout_s,
                                    span=root)
            except Overloaded as e:
                root.end(outcome=CODE_OVERLOADED)
                with olock:
                    outcomes[CODE_OVERLOADED] += 1
                    if e.retry_after_s is not None:
                        retry_hints.append(e.retry_after_s)
                continue
            except DeadlineExceeded:
                root.end(outcome=CODE_DEADLINE)
                with olock:
                    outcomes[CODE_DEADLINE] += 1
                continue
            except Exception:
                root.end(outcome="error")
                with olock:
                    outcomes["error"] += 1
                continue
            root.end(outcome="ok")
            with olock:
                outcomes["ok"] += 1
                latencies.append(time.monotonic() - t0)

    prober = threading.Thread(target=probe_depth, daemon=True)
    prober.start()
    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(cfg.clients)]
    # Every request a client makes is deadline-bounded (timeout_s), so a
    # client that outlives its whole budget is WEDGED — join with that
    # budget instead of forever, and let the all_accounted invariant fail
    # loudly instead of hanging the harness.
    client_budget_s = cfg.requests_per_client * cfg.timeout_s + 30.0
    try:
        for t in threads:
            t.start()
        join_deadline = time.monotonic() + client_budget_s
        for t in threads:
            t.join(timeout=max(0.1, join_deadline - time.monotonic()))
    finally:
        stop_probe.set()
        prober.join(timeout=5.0)
        if own:
            service.stop()
    stats = service.service_stats()
    total = cfg.clients * cfg.requests_per_client
    em = service.engine.metrics
    svc_label = type(service).__name__.lower()
    elapsed_s = time.perf_counter() - t_start
    # One closing sample so the windowed signals cover the whole drill.
    sampler.sample_now()
    slo_snap = service.slo.snapshot(windows=(10.0, 60.0),
                                    group_by=("role",))
    slo_deltas = {k: slo_snap["totals"][k] - totals_before[k]
                  for k in slo_snap["totals"]}
    judged = slo_deltas["judged"]
    throughput_rps = outcomes["ok"] / elapsed_s if elapsed_s else 0.0
    goodput_rps = slo_deltas["goodput"] / elapsed_s if elapsed_s else 0.0

    def _q(name, q):
        v = REGISTRY.quantile(name, q, service=svc_label)
        return round(v, 4) if v is not None else None

    report = {
        "scenario": "overload",
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(elapsed_s, 3),
        "outcomes": outcomes,
        "admitted_latency_ms": _pcts(latencies),
        "retry_after_hint_s": (round(min(retry_hints), 3)
                               if retry_hints else None),
        "max_queue_depth_observed": depth_max[0],
        "service": stats,
        # Continuous-batching observability (engine join accounting + the
        # rbg_serving_batch_occupancy / rbg_serving_join_latency_seconds
        # registry series this service labeled).
        "continuous_batching": {
            "joins": em.get("joins", 0),
            "unified_steps": em.get("unified_steps", 0),
            "join_wait_steps_max": em.get("join_wait_steps_max", 0),
            "join_excess_steps_max": em.get("join_excess_steps_max", 0),
            "batch_occupancy_p50": _q(metric_names.SERVING_BATCH_OCCUPANCY,
                                      0.5),
            "join_latency_p50_s": _q(
                metric_names.SERVING_JOIN_LATENCY_SECONDS, 0.5),
            "join_latency_p95_s": _q(
                metric_names.SERVING_JOIN_LATENCY_SECONDS, 0.95),
        },
        # SLO attainment + goodput (obs/slo.py): per-role windowed
        # attainment, this run's verdict deltas, and the windowed signals
        # the sampler accumulated through the drill.
        "slo": {
            "targets": slo_snap["targets"],
            "judged": judged,
            "verdicts": slo_deltas,
            "per_role_60s": slo_snap["windows"]["60s"],
        },
        # The headline the autoscaler will steer on: raw completion
        # throughput vs throughput that MET the SLO. Under deliberate
        # overload the gap between these two is the cost of queueing.
        "goodput_vs_throughput": {
            "throughput_rps": round(throughput_rps, 3),
            "goodput_rps": round(goodput_rps, 3),
            "goodput_fraction": (round(slo_deltas["goodput"] / judged, 4)
                                 if judged else None),
        },
        "invariants": {
            # The three promises the overload machinery makes:
            "queue_bounded": depth_max[0] <= cfg.max_queue,
            "all_accounted": sum(outcomes.values()) == total,
            "shed_instead_of_queued": (outcomes[CODE_OVERLOADED] == 0
                                       or stats["shed_total"] > 0),
            # Continuous admission (the ragged-batching promise): under
            # the mixed trace, no request the engine admitted waited more
            # than ONE step beyond page/slot availability.
            "continuous_admission": em.get("join_excess_steps_max", 0) <= 1,
            # Every request that finished generation was SLO-judged
            # exactly once — the accounting contract the attainment and
            # goodput numbers stand on. Shed / deadline / error outcomes
            # are accounted in their own counters, never judged.
            "slo_accounted": judged == outcomes["ok"],
        },
    }
    return report


# ---- KV transfer plane scenario --------------------------------------------


@dataclasses.dataclass
class KVStreamConfig:
    """Slow-link drill for the KVCache-centric transfer plane
    (rbg_tpu/kvtransfer): a PD pair streams chunked KV over a slow, lossy,
    reordering link — with one stream truncated mid-transfer — and the
    drill asserts the plane's three promises:

    * ``kv_stream_overlap`` — decode starts before the transfer plane is
      done: a row's first decode step lands before its stream's close
      frame arrives on the slow link (coverage-based admission, never
      wait-for-FIN).
    * ``directory_consistent`` — no cluster prefix-directory lookup
      returns an evicted prefix or an invalidated (preempted-slice)
      backend.
    * ``zero_dropped_streams`` — the truncated stream surfaces as a
      structured error and is retried token-exact; every request
      completes with outputs BIT-IDENTICAL to a unified engine.
    """

    requests: int = 6
    prompt_len: int = 48            # several pages at page_size 8
    max_new_tokens: int = 8
    slow_link_delay_s: float = 0.05  # per-frame; the overlap window
    dup_rate: float = 0.25
    # Reordering still happens at window 1 (adjacent pairs swap on every
    # flush) — but the window must stay SMALLER than the post-token tail
    # (two chunks for the tiny model's last page group), or the lossy
    # wrapper's FIN flush delivers the whole tail and the close frame as
    # one burst: no admission policy can overlap a window that never
    # opens, and the drill would be testing the link model, not the
    # plane.
    reorder_window: int = 1
    truncate_nth_stream: int = 2    # this stream dies mid-transfer
    model: str = "tiny"
    # Layer-sliced admission: layer-ordered chunking (layer_split) plus
    # admit-at-layer-k (admit_layers > 0) — the decode side starts the
    # first step as a layer-windowed chain under the transfer tail. The
    # report surfaces per-stream layer-coverage-at-admit; the
    # bit_identical / zero_dropped_streams invariants are UNCHANGED (a
    # mid-chain stream cut cancels the row pre-emit and retries
    # token-exact). admit_layers=0 restores whole-coverage admission.
    layer_split: int = 1
    admit_layers: int = 1
    # Modeled bandwidth of the inner link (FakeICITransport under the
    # lossy wrapper). Without per-byte pacing the lossy wrapper's
    # control-frame flushes deliver the whole transfer tail as one
    # burst — full coverage lands the same instant as layer-k coverage
    # and the layer-sliced window never opens.
    link_bytes_per_s: float = 2e5


def run_kv_stream(cfg: KVStreamConfig) -> dict:
    import numpy as np

    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.engine import Engine
    from rbg_tpu.engine.kvpool import KVPoolStore
    from rbg_tpu.engine.pd import PDStreamPair
    from rbg_tpu.kvtransfer import (FakeICITransport, PrefixDirectory,
                                    SlowLossyTransport)

    page_size = 8
    ecfg = dict(model=cfg.model, page_size=page_size, num_pages=256,
                max_batch=4, max_seq_len=256, prefill_chunk=16,
                use_pallas="never")
    rng = np.random.RandomState(11)
    eng_ref = Engine(EngineConfig(enable_radix_cache=False, **ecfg))
    vocab = eng_ref.mcfg.vocab_size
    prompts = [rng.randint(1, vocab, size=cfg.prompt_len).tolist()
               for _ in range(cfg.requests)]
    sp = SamplingParams(max_new_tokens=cfg.max_new_tokens)
    expect = eng_ref.generate(prompts, sp)

    directory = PrefixDirectory(page_size=page_size)
    # The shared prefix store doubles as the drill's eviction source: a
    # budget small enough that later puts evict earlier prefixes, whose
    # directory keys must be invalidated with them.
    pool = KVPoolStore(page_size, max_bytes=1 << 18, directory=directory)
    link = SlowLossyTransport(FakeICITransport(
                                  bytes_per_s=cfg.link_bytes_per_s,
                                  latency_s=0.0005),
                              delay_s=cfg.slow_link_delay_s,
                              reorder_window=cfg.reorder_window,
                              dup_rate=cfg.dup_rate,
                              truncate_nth_stream=cfg.truncate_nth_stream,
                              truncate_after_bytes=1 << 12, seed=7)
    pair = PDStreamPair(EngineConfig(**ecfg),
                        params=eng_ref.params, transport=link,
                        layer_split=cfg.layer_split,
                        admit_layers=cfg.admit_layers)
    pair.prefill.pool = pool
    pool.page_size = page_size
    pair.prefill.directory = directory
    pair.prefill.advertise_addr = "10.0.0.1:9000"
    pair.prefill.slice_id = "slice-a"

    # Two warm passes (same prompt) through the SAME plane, slow link
    # included, compile the prefill/inject/decode programs — the second
    # hits the pool prefix published by the first, compiling the
    # prefix-import scatter too. The drill then measures the transfer
    # plane, not jit compiles (which would mask overlap).
    warm_prompt = rng.randint(1, vocab,
                              size=cfg.prompt_len).tolist()
    for _ in range(2):
        pair.generate_one(warm_prompt, sp, stream=True,
                          recv_timeout=120.0, max_retries=2)
    if cfg.admit_layers > 0:
        # Layer-sliced engagement is timing-dependent; the warm passes
        # may have taken the plain path, so compile the window chain
        # explicitly (masked writes — live pool unchanged).
        pair.decode.warm_layer_sliced(cfg.admit_layers)
    # Everything above is the blessed warmup set; the measured phase
    # below must not compile a cataloged program (no-op unless
    # --jitwatch armed the hooks).
    from rbg_tpu.utils import jitwatch as _jitwatch
    _jitwatch.warmup_complete()

    results = []
    failures = []
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        try:
            results.append(pair.generate_one(p, sp, stream=True,
                                             recv_timeout=60.0,
                                             max_retries=2))
        except Exception as e:  # noqa: BLE001 — account, don't crash
            failures.append(f"request {i}: {type(e).__name__}: {e}")
            results.append(None)
    elapsed = time.perf_counter() - t0

    bit_identical = all(r is not None and r["tokens"] == e
                        for r, e in zip(results, expect))
    overlaps = [bool(r and r.get("overlap")) for r in results]
    retried = sum(r["retries"] for r in results if r)

    # Directory consistency sweep #1 (evictions): every holder claim the
    # directory still makes must be backed by the pool actually holding
    # at least that many prefix tokens.
    dir_vs_pool_ok = True
    for p in prompts:
        matched, holders = directory.lookup(p)
        if matched and holders:
            pool_tokens = pool.match(p)[0]
            if pool_tokens < matched:
                dir_vs_pool_ok = False
    # Sweep #2 (slice preemption): invalidating the slice must empty
    # every lookup — the DisruptionController's wire into the directory.
    directory.invalidate_slice("slice-a", reason="preemption")
    post_preempt_ok = all(directory.lookup(p)[1] == [] for p in prompts)

    report = {
        "scenario": "kvstream",
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(elapsed, 3),
        "requests": {
            "total": cfg.requests,
            "completed": sum(1 for r in results if r),
            "stream_retries": retried,
            "failures": failures,
        },
        "transfer": {
            "bytes_per_request": (results[0]["bytes"]
                                  if results and results[0] else 0),
            "overlap_requests": sum(overlaps),
            "admit_lead_ms": _pcts([r["admit_lead_s"] for r in results
                                    if r and r["admit_lead_s"] is not None]),
            "t_first_decode_ms": _pcts([r["t_first_decode"] for r in results
                                        if r and r["t_first_decode"]]),
            # Layer-sliced admission: how deep device coverage was when
            # each stream's row was admitted (None = the stream reached
            # full coverage first and took the plain path — lossy links
            # make engagement per-stream, not guaranteed).
            "layer_admit": {
                "admit_layers": cfg.admit_layers,
                "engaged_requests": sum(
                    1 for r in results
                    if r and r.get("layers_at_admit") is not None),
                "coverage_at_admit": [
                    (None if not r or r.get("layers_at_admit") is None
                     else [r["layers_at_admit"], r["total_layers"]])
                    for r in results],
            },
        },
        "pool": pool.stats(),
        "directory": directory.stats(),
        "bit_identical": bit_identical,
        "invariants": {
            # Decode began while this row's stream was still closing on
            # the slow link — for EVERY completed row (coverage-based
            # admission is unconditional, not lucky).
            "kv_stream_overlap": bool(overlaps) and all(
                o for o, r in zip(overlaps, results) if r),
            "directory_consistent": dir_vs_pool_ok and post_preempt_ok,
            # The truncated stream was retried, nothing was dropped, and
            # every output matches the unified reference bit-for-bit.
            "zero_dropped_streams": (not failures and bit_identical
                                     and retried >= 1),
        },
    }
    return report


# ---- KV cache-hierarchy scenario -------------------------------------------


@dataclasses.dataclass
class PrefixCacheConfig:
    """Mooncake-tier cache-hierarchy drill: a deliberately undersized
    device page pool serves system-prompt-heavy traffic (long shared
    prefixes, unique suffixes, round-robin across prefix groups so every
    admission evicts someone else's prefix), with the host-DRAM spill
    tier underneath and predictive early rejection at admission. Four
    promises:

    * ``tier_accounting`` — every cached page lives in exactly one tier:
      the host tier's lifetime identity closes (spilled == promoted +
      evicted + resident) and no prompt's pages are simultaneously
      device- and host-resident.
    * ``directory_consistent`` — every tier-tagged directory claim is
      backed by the tiers actually covering at least that depth.
    * ``early_reject_before_prefill`` — rejected requests consumed ZERO
      prefill steps: the engine's prefill-token counter accounts exactly
      for the COMPLETED requests' prompts net of their prefix hits.
    * ``zero_dropped_streams`` — every submission either completes
      bit-identical to the device-only reference or is a structured
      overload rejection with a retry hint; nothing times out or errors.
    """

    system_prompts: int = 3
    prefix_len: int = 64            # shared prefix (pages of 8)
    suffix_len: int = 16
    requests_per_prefix: int = 4
    max_new_tokens: int = 6
    num_pages: int = 40             # undersized on purpose: ~1.2 prompts
    host_tier_bytes: int = 1 << 26
    burst_clients: int = 10         # early-rejection burst
    slo_ttft_s: float = 0.6
    early_reject_factor: float = 1.0
    model: str = "tiny"


def run_prefix_cache(cfg: PrefixCacheConfig) -> dict:
    import threading

    import numpy as np

    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.engine import Engine
    from rbg_tpu.engine.protocol import Overloaded
    from rbg_tpu.engine.service import EngineService
    from rbg_tpu.kvtransfer import PrefixDirectory

    page_size = 8
    base = dict(model=cfg.model, page_size=page_size, max_batch=4,
                max_seq_len=256, prefill_chunk=16, use_pallas="never")
    rng = np.random.RandomState(17)
    probe = Engine(EngineConfig(num_pages=256, enable_radix_cache=False,
                                **base))
    vocab = probe.mcfg.vocab_size
    prefixes = [rng.randint(1, vocab, size=cfg.prefix_len).tolist()
                for _ in range(cfg.system_prompts)]
    # Round-robin across prefix groups: admitting group B's prompt must
    # evict group A's prefix from the undersized device pool — the exact
    # pattern that threw prefixes away forever before the host tier.
    prompts = []
    for r in range(cfg.requests_per_prefix):
        for pre in prefixes:
            prompts.append(pre + rng.randint(
                1, vocab, size=cfg.suffix_len).tolist())
    sp = SamplingParams(max_new_tokens=cfg.max_new_tokens)
    expect = {tuple(p): probe.generate([p], sp)[0] for p in prompts}

    # --- phase A: hierarchy correctness + accounting under churn ---
    directory = PrefixDirectory(page_size=page_size)
    eng = Engine(EngineConfig(num_pages=cfg.num_pages,
                              host_tier_bytes=cfg.host_tier_bytes, **base))
    eng.host_tier.wire_directory(directory, "10.0.0.1:9000",
                                 slice_id="slice-a")
    t0 = time.perf_counter()
    outs = [eng.generate([p], sp)[0] for p in prompts]
    outs += [eng.generate([p], sp)[0] for p in prompts]   # host-hit pass
    elapsed = time.perf_counter() - t0
    bit_identical = all(o == expect[tuple(p)]
                        for o, p in zip(outs, prompts + prompts))
    tier = eng.host_tier.stats()
    # Exactly-one-tier: the lifetime identity closes AND no prompt has
    # pages resident in both tiers at once (host payload may only begin
    # where the device-resident prefix ends; radix eviction is
    # leaf-first, so device keeps a prefix of the path, host the rest).
    overlap_free = True
    dir_ok = True
    for p in prompts:
        d = eng.radix.peek(p)
        h0 = eng.host_tier.peek(p, 0)
        if d > 0 and h0 > 0:
            overlap_free = False
        dir_matched, _detail = directory.lookup_detail(p)
        if dir_matched > d + eng.host_tier.peek(p, d):
            dir_ok = False
    accounting = (eng.host_tier.accounting_closes()
                  and tier["spilled_pages"] > 0
                  and tier["promoted_pages"] > 0)

    # --- phase B: predictive early rejection under a burst ---
    svc = EngineService(EngineConfig(
        num_pages=cfg.num_pages, host_tier_bytes=cfg.host_tier_bytes,
        early_reject="auto", slo_ttft_s=cfg.slo_ttft_s,
        early_reject_factor=cfg.early_reject_factor, **base))
    try:
        # Warm the jit cache first (the predictor must learn steady-state
        # prefill throughput, not compile stalls — a cold service would
        # predict multi-second TTFTs and reject its very first traffic),
        # then train the completion/prefill rates on real sequential
        # requests.
        svc.warmup(input_len=32, out_len=2)
        for p in prompts[:4]:
            svc.submit(p, sp, timeout=120.0)
        pf_base = svc.engine.metrics["prefill_tokens"]
        hit_base = (svc.engine.metrics["radix_hit_tokens"]
                    + svc.engine.metrics["host_hit_tokens"])
        results = {}
        lock = threading.Lock()

        def client(i: int, prompt):
            try:
                tokens, _ = svc.submit(prompt, sp, timeout=120.0)
                out = ("ok", tokens)
            except Overloaded as e:
                out = ("shed", getattr(e, "retry_after_s", None))
            except Exception as e:  # noqa: BLE001 — account, don't crash
                out = ("error", f"{type(e).__name__}: {e}")
            with lock:
                results[i] = out
        burst = [prompts[i % len(prompts)]
                 for i in range(cfg.burst_clients * 2)]
        threads = [threading.Thread(target=client, args=(i, p), daemon=True)
                   for i, p in enumerate(burst)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180.0)
        wedged = [t for t in threads if t.is_alive()]
        completed = [(i, burst[i]) for i, (kind, _) in results.items()
                     if kind == "ok"]
        shed = [(i, r) for i, (kind, r) in results.items() if kind == "shed"]
        errors = [(i, r) for i, (kind, r) in results.items()
                  if kind == "error"]
        early_rejects = svc.counters["early_rejects"]
        # The zero-prefill-for-rejected identity: every prefill token the
        # engine spent during the burst is attributable to a COMPLETED
        # request's prompt net of its prefix hits. A rejected request
        # that touched prefill would break the equality.
        pf_spent = svc.engine.metrics["prefill_tokens"] - pf_base
        hits = (svc.engine.metrics["radix_hit_tokens"]
                + svc.engine.metrics["host_hit_tokens"]) - hit_base
        pf_expected = sum(len(p) for _, p in completed) - hits
        burst_identical = all(
            results[i][1] == expect[tuple(p)] for i, p in completed)
        shed_have_hints = all(r is not None and r > 0 for _, r in shed)
    finally:
        svc.stop()

    return {
        "scenario": "prefixcache",
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(elapsed, 3),
        "hierarchy": {
            "requests": len(prompts) * 2,
            "host_tier": tier,
            "device_tier_pages": eng.radix.cached_pages,
            "radix_hit_tokens": eng.metrics["radix_hit_tokens"],
            "host_hit_tokens": eng.metrics["host_hit_tokens"],
            "directory": directory.stats(),
        },
        "burst": {
            "submitted": len(burst),
            "completed": len(completed),
            "shed": len(shed),
            "early_rejects": early_rejects,
            "errors": [f"client {i}: {msg}" for i, msg in errors],
            "wedged_clients": len(wedged),
            "prefill_tokens_spent": pf_spent,
            "prefill_tokens_expected": pf_expected,
        },
        "bit_identical": bit_identical and burst_identical,
        "invariants": {
            "tier_accounting": accounting and overlap_free,
            "directory_consistent": dir_ok,
            "early_reject_before_prefill": (
                early_rejects > 0 and pf_spent == pf_expected
                and shed_have_hints),
            "zero_dropped_streams": (
                not errors and not wedged and bit_identical
                and burst_identical and len(completed) > 0),
        },
    }


# ---- SLO-driven autoscaling scenario ---------------------------------------


@dataclasses.dataclass
class AutoscaleStressConfig:
    """Capacity-follows-load drill: a diurnal + burst Poisson trace
    against a LIVE mini-plane (fake fleet, real group/instance/scheduler
    controllers, real AutoscaleController writing real ScalingAdapters).
    A simulated serving role turns ready-replica capacity into completed
    requests, judges them against an SLO, and publishes the same windowed
    signals a real engine would — the autoscaler closes the loop, and the
    drill asserts that it did: targets rise within an evaluation period
    of the burst, fall after it, scale-down drains without dropping one
    in-flight stream, every finished request is judged, and goodput never
    collapses."""

    duration_s: float = 14.0
    tick_s: float = 0.05
    # Offered-load profile: slow diurnal sine from base to peak across
    # the run, plus a flat burst on top inside the burst window.
    base_rps: float = 10.0
    peak_rps: float = 28.0
    burst_rps: float = 85.0
    burst_start_frac: float = 0.40
    burst_end_frac: float = 0.62
    # Simulated role capacity: each ready, non-draining replica completes
    # this many requests per second.
    per_replica_rps: float = 12.0
    queue_limit: int = 120          # admission bound — beyond this, shed
    slo_wait_s: float = 0.6         # TTFT target the sim judges against
    min_replicas: int = 1
    max_replicas: int = 10
    eval_period_s: float = 0.4
    window_s: float = 2.0
    stale_after_s: float = 1.5
    up_stabilization_s: float = 0.3
    down_stabilization_s: float = 2.0
    cooldown_s: float = 0.5
    drain_s: float = 6.0            # scale-down drain window
    # Without the autoscaler this trace pins attainment near zero from
    # the burst on; the floor asserts the loop kept a large fraction of
    # all requests green. Observed run-to-run range has drifted with host
    # speed (~0.55-0.59 historically, ~0.44-0.45 on slower boxes), so the
    # floor sits below the slow-box band — it catches the no-autoscaler
    # collapse (near zero), not wall-clock noise.
    goodput_floor: float = 0.40
    seed: int = 7
    timeout_s: float = 60.0


def _poisson(rng, lam: float) -> int:
    """Knuth's Poisson sampler (lam is small — per-tick arrivals)."""
    if lam <= 0:
        return 0
    limit = __import__("math").exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def run_autoscale(cfg: AutoscaleStressConfig) -> dict:
    import math

    from rbg_tpu.api.group import IdentityMode, ScalingAdapterHook
    from rbg_tpu.autoscale import AutoscaleConfig, RolePolicy
    from rbg_tpu.obs import slo as slo_mod, timeseries
    from rbg_tpu.obs.slo import SLOTargets, SLOTracker
    from rbg_tpu.runtime.controllers.scalingadapter import adapter_name

    role_name = "serve"
    group_name = "asc"
    rng = __import__("random").Random(cfg.seed)

    # Shared sim state read by the controller's hooks. Whole-dict
    # reassignment keeps reads torn-free without a lock (the hooks only
    # ever read the current reference).
    hook_state = {"queue_depth": 0.0, "estimated_wait_s": 0.0}
    stream_view: Dict[str, float] = {}

    def extras_fn(_role):
        return hook_state

    def inflight_fn(pod_name):
        return stream_view.get(pod_name, 0.0)

    policy = RolePolicy(
        role=role_name, min_replicas=cfg.min_replicas,
        max_replicas=cfg.max_replicas,
        target_rps_per_replica=cfg.per_replica_rps,
        attainment_target=0.9, min_judged=3,
        max_estimated_wait_s=cfg.slo_wait_s,
        up_stabilization_s=cfg.up_stabilization_s,
        down_stabilization_s=cfg.down_stabilization_s,
        cooldown_s=cfg.cooldown_s)
    auto_cfg = AutoscaleConfig(
        roles={role_name: policy}, eval_period_s=cfg.eval_period_s,
        window_s=cfg.window_s, stale_after_s=cfg.stale_after_s,
        extras_fn=extras_fn, inflight_streams_fn=inflight_fn)

    slo_mod.reset_trackers()
    tracker = SLOTracker(SLOTargets(ttft_s=cfg.slo_wait_s, tpot_s=0.5),
                         component="autoscale-sim")
    sampler = timeseries.get_sampler()

    plane = ControlPlane(backend="fake", autoscale=auto_cfg)
    make_tpu_nodes(plane.store, slices=4, hosts_per_slice=4)
    role = simple_role(role_name, replicas=cfg.min_replicas)
    role.identity = IdentityMode.RANDOM      # stateless: drain lifecycle
    role.drain_seconds = cfg.drain_s
    role.scaling_adapter = ScalingAdapterHook(
        enabled=True, min_replicas=cfg.min_replicas,
        max_replicas=cfg.max_replicas)
    counters_before = {
        name: REGISTRY.counter(name, role=role_name)
        for name in (metric_names.SERVING_SHED_TOTAL,
                     metric_names.SERVING_REQUESTS_FINISHED_TOTAL)}
    decisions_before = {
        d: REGISTRY.counter(metric_names.AUTOSCALE_DECISIONS_TOTAL,
                            role=role_name, direction=d)
        for d in ("up", "down")}
    t_run = time.perf_counter()
    plane.start()
    inv: Dict[str, bool] = {}
    curve: List[dict] = []
    dropped = [0]
    finished_total = [0]
    shed_total = [0]
    judged_before = tracker.judged_total()
    sa_name = adapter_name(group_name, role_name)
    try:
        plane.apply(make_group(group_name, role))
        plane.wait_group_ready(group_name, timeout=cfg.timeout_s)
        plane.wait_for(
            lambda: plane.store.get("ScalingAdapter", "default", sa_name),
            timeout=cfg.timeout_s, desc="auto-created scaling adapter")

        def role_pods():
            return [p for p in plane.store.list("Pod", namespace="default")
                    if p.metadata.labels.get(C.LABEL_GROUP_NAME) == group_name
                    and p.metadata.labels.get(C.LABEL_ROLE_NAME) == role_name]

        def is_draining(p) -> bool:
            return (p.metadata.annotations.get(C.ANN_LIFECYCLE_STATE)
                    == C.LIFECYCLE_PREPARING_DELETE)

        def target_now() -> int:
            sa = plane.store.get("ScalingAdapter", "default", sa_name,
                                 copy_=False)
            if sa is not None and sa.spec.replicas is not None:
                return sa.spec.replicas
            g = plane.store.get("RoleBasedGroup", "default", group_name,
                                copy_=False)
            return g.spec.role(role_name).replicas if g is not None else 0

        streams: Dict[str, float] = {}   # pod -> in-flight streams
        queue = 0.0
        burst_t0 = cfg.duration_s * cfg.burst_start_frac
        burst_t1 = cfg.duration_s * cfg.burst_end_frac
        target_pre_burst: Optional[int] = None
        burst_react_s: Optional[float] = None
        t0 = time.perf_counter()
        while True:
            now = time.perf_counter() - t0
            if now >= cfg.duration_s:
                break
            frac = now / cfg.duration_s
            lam = (cfg.base_rps + (cfg.peak_rps - cfg.base_rps)
                   * math.sin(math.pi * frac) ** 2)
            in_burst = burst_t0 <= now < burst_t1
            if in_burst:
                lam += cfg.burst_rps
            arrivals = _poisson(rng, lam * cfg.tick_s)

            pods = role_pods()
            live = {p.metadata.name for p in pods if p.active}
            serving = [p for p in pods
                       if p.active and p.running_ready and not is_draining(p)]
            draining = [p for p in pods if p.active and is_draining(p)]

            # Streams: lost pods with in-flight streams are DROPS (the
            # invariant); draining pods finish theirs and ack; serving
            # pods carry a stream population proportional to load.
            for name in [n for n in streams if n not in live]:
                if streams[name] > 0:
                    dropped[0] += int(streams[name])
                del streams[name]
            for p in draining:
                n = streams.get(p.metadata.name, 0.0)
                if n > 0:
                    streams[p.metadata.name] = max(0.0, n - 2.0)
                if streams.get(p.metadata.name, 0.0) <= 0:
                    iname = p.metadata.labels.get(C.LABEL_INSTANCE_NAME)
                    if iname:
                        def ack(i):
                            if i.metadata.annotations.get(
                                    C.ANN_DRAIN_COMPLETE) == "true":
                                return False
                            i.metadata.annotations[
                                C.ANN_DRAIN_COMPLETE] = "true"
                            return True
                        try:
                            plane.store.mutate("RoleInstance", "default",
                                               iname, ack)
                        except Exception:
                            pass
            want_streams = min(len(serving) * 4, int(lam / 4) + 1)
            have = sum(int(streams.get(p.metadata.name, 0.0))
                       for p in serving)
            for p in serving:
                if have >= want_streams:
                    break
                streams[p.metadata.name] = streams.get(p.metadata.name,
                                                       0.0) + 1
                have += 1
            # Rebinding the locals the closures capture is the publish
            # step: extras_fn / inflight_fn read the current dicts.
            stream_view = dict(streams)

            # Service model: capacity completes queue, overflow sheds.
            cap_rps = len(serving) * cfg.per_replica_rps
            queue += arrivals
            completed = min(queue, cap_rps * cfg.tick_s)
            queue -= completed
            wait_s = queue / cap_rps if cap_rps > 0 else float(
                cfg.slo_wait_s * 10)
            overflow = max(0.0, queue - cfg.queue_limit)
            if overflow >= 1.0:
                n_shed = int(overflow)
                queue -= n_shed
                shed_total[0] += n_shed
                REGISTRY.inc(metric_names.SERVING_SHED_TOTAL, float(n_shed),
                             role=role_name)
            n_done = int(round(completed))
            if n_done:
                finished_total[0] += n_done
                REGISTRY.inc(metric_names.SERVING_REQUESTS_FINISHED_TOTAL,
                             float(n_done), role=role_name)
                REGISTRY.inc(metric_names.SERVING_TOKENS_TOTAL,
                             float(n_done * 8), role=role_name)
                for _ in range(n_done):
                    tracker.judge(wait_s, 0.01, role=role_name)
            hook_state = {"queue_depth": queue, "estimated_wait_s": wait_s}
            sampler.sample_now()

            tgt = target_now()
            if in_burst and target_pre_burst is None:
                target_pre_burst = tgt
            if (target_pre_burst is not None and burst_react_s is None
                    and tgt > target_pre_burst):
                burst_react_s = round(now - burst_t0, 3)
            curve.append({
                "t": round(now, 3),
                "offered_rps": round(lam, 2),
                "capacity_rps": round(cap_rps, 2),
                "queue": round(queue, 1),
                "target": tgt,
                "actual": len(serving),
            })
            time.sleep(cfg.tick_s)
        status = (plane.autoscale_controller.status()
                  if plane.autoscale_controller else {})
    finally:
        plane.stop()

    judged = tracker.judged_total() - judged_before
    totals = tracker.totals()
    goodput_frac = totals["goodput"] / judged if judged else None
    peak_target = max((c["target"] for c in curve), default=0)
    end_target = curve[-1]["target"] if curve else 0
    # Deltas from the pre-run snapshot: the registry is process-global,
    # and an in-process caller (a test suite) may have scaled this role
    # name before — absolute values would let a prior run's scale-down
    # satisfy THIS run's invariant.
    decisions = {
        d: REGISTRY.counter(metric_names.AUTOSCALE_DECISIONS_TOTAL,
                            role=role_name, direction=d)
        - decisions_before[d]
        for d in ("up", "down")}
    # Reaction bound: pressure must be noticed at one evaluation and
    # actuated by the next once the up-stabilization window passed —
    # two evaluation periods end to end, plus scheduling slack.
    react_bound = 2 * cfg.eval_period_s + cfg.up_stabilization_s + 0.75
    inv["capacity_follows_load"] = (
        burst_react_s is not None and burst_react_s <= react_bound)
    inv["targets_fell_after_burst"] = (end_target < peak_target
                                      and decisions["down"] >= 1)
    inv["zero_dropped_streams"] = dropped[0] == 0
    inv["slo_accounted"] = judged == finished_total[0]
    inv["goodput_floor"] = (goodput_frac is not None
                            and goodput_frac >= cfg.goodput_floor)
    return {
        "scenario": "autoscale",
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(time.perf_counter() - t_run, 3),
        "burst_react_s": burst_react_s,
        "burst_react_bound_s": round(react_bound, 3),
        "peak_target": peak_target,
        "end_target": end_target,
        "requests": {
            "finished": finished_total[0],
            "shed": shed_total[0],
            "judged": judged,
            "goodput_fraction": (round(goodput_frac, 4)
                                 if goodput_frac is not None else None),
            "dropped_streams": dropped[0],
        },
        "decisions": {k: round(v, 1) for k, v in decisions.items()},
        "autoscale_status": status,
        "curve": curve,
        "counters_delta": {
            name: round(REGISTRY.counter(name, role=role_name) - v, 1)
            for name, v in counters_before.items()},
        "invariants": inv,
    }


# ---- adaptive topology (agg<->disagg) scenario -----------------------------


@dataclasses.dataclass
class TopoFlipConfig:
    """Adaptive-topology drill: a load-mix-shifting Poisson trace
    (chat-heavy → long-prompt-heavy → mixed) against a live mini-plane
    whose group can flip between the unified shape and the PD-disagg
    shape at runtime (rbg_tpu/topology). The trace runs INTERLEAVED
    against both static shapes, and the drill asserts the subsystem's
    promises:

    * ``zero_dropped_streams`` — no in-flight stream dies across any
      flip: old-shape pods drain through PreparingDelete, streams finish;
    * ``bit_identical`` — a PD stream cut mid-flip re-routes token-exact
      through the PR-10 bundle fallback (real tiny-engine leg);
    * ``topology_converged`` — the controller flips to the winning shape
      within the ratio window + stabilization + 2 evaluation periods of
      a sustained mix shift;
    * ``no_flap`` — bounded flips across the whole trace (the mixed tail
      sits in the deadband and must NOT flip);
    * ``goodput_adaptive_ge_static`` — adaptive goodput ≥ both static
      shapes on the full trace (median of interleaved reps,
      trimmed-spread gated per the fleet A/B discipline; reps >= 2).
    """

    duration_s: float = 15.0
    tick_s: float = 0.05
    rps: float = 40.0
    # Phase boundaries (fractions of the trace) and the long-document
    # fraction of arrivals inside each phase. chat ~ ratio 1.1 (unified
    # pressure), long ~ ratio 15.6 (disagg pressure), mixed ~ ratio 4.5
    # (deadband: HOLD, the anti-flap leg).
    phase_fracs: tuple = (0.30, 0.40, 0.30)
    long_frac_by_phase: tuple = (0.02, 0.95, 0.15)
    chat_tokens: tuple = (32, 64)      # (prompt, decode) tokens
    long_tokens: tuple = (2048, 128)
    # Service model: each serving replica provides this many cost units
    # per second; a completed request costs units by (shape, class) —
    # unified pays a prefill-monopolizes-decode tax on long prompts,
    # disagg pays the KV-transfer tax on short chat turns (the paper's
    # crossover, scaled down).
    per_replica_units: float = 14.0
    cost_unified: tuple = (1.0, 4.0)   # (chat, long)
    cost_disagg: tuple = (2.0, 1.2)
    unified_replicas: int = 4
    prefill_replicas: int = 2
    decode_replicas: int = 2
    queue_limit: int = 160
    slo_wait_s: float = 0.7
    drain_s: float = 2.0
    eval_period_s: float = 0.3
    window_s: float = 2.0
    stale_after_s: float = 1.5
    disagg_stab_s: float = 0.45
    unified_stab_s: float = 0.45
    cooldown_s: float = 1.5
    disagg_ratio: float = 6.0
    unified_ratio: float = 2.0
    max_switch_cost_s: float = 5.0
    kv_bytes_per_stream: float = 1 << 20
    link_bytes_per_s: float = 200e6
    max_flips: int = 2
    reps: int = 3                      # interleaved adaptive/static reps
    spread_max: float = 0.45
    attempts: int = 2                  # whole-A/B retries (bimodal box)
    token_exact: bool = True           # run the real-engine PD leg
    seed: int = 11
    timeout_s: float = 60.0


def _run_topoflip_rep(cfg: TopoFlipConfig, mode: str) -> dict:
    """One trace repetition. ``mode``: adaptive (TopologyController
    live), unified / disagg (static shape, no controller)."""
    import collections

    from rbg_tpu.api import constants as C2
    from rbg_tpu.api.group import IdentityMode, ScalingAdapterHook
    from rbg_tpu.obs import timeseries
    from rbg_tpu.topology import (
        GroupTopology, POSTURE_DISAGG, POSTURE_UNIFIED, TopologyConfig,
        TopologyPolicyConfig,
    )

    group_name = "topo"
    gt = GroupTopology(
        group=group_name, unified_replicas=cfg.unified_replicas,
        prefill_replicas=cfg.prefill_replicas,
        decode_replicas=cfg.decode_replicas)
    rng = __import__("random").Random(cfg.seed)
    sampler = timeseries.get_sampler()

    # ---- shared sim state the controller hooks read ----
    active_roles = ({gt.unified_role} if mode != "disagg"
                    else {gt.prefill_role, gt.decode_role})
    arrivals_win = collections.deque()   # (t, prompt_toks, decode_toks)
    done_win = collections.deque()       # completion stamps
    # One-slot publish: the trace loop computes the decision inputs each
    # tick and stores a FRESH dict here (atomic slot write); the
    # controller thread's signals_fn only ever reads a frozen snapshot —
    # it must never iterate the live deques the loop is mutating.
    published = {"sig": {"fresh": True, "prefill_decode_ratio": None,
                         "judged": 0,
                         "link_bytes_per_s": cfg.link_bytes_per_s}}

    def candidacy_fn(_group, role, active):
        if active:
            active_roles.add(role)
        else:
            active_roles.discard(role)

    def signals_fn(_gt):
        return dict(published["sig"])

    topo_cfg = None
    if mode == "adaptive":
        topo_cfg = TopologyConfig(
            groups=[gt],
            policy=TopologyPolicyConfig(
                disagg_ratio=cfg.disagg_ratio,
                unified_ratio=cfg.unified_ratio,
                min_judged=3,
                disagg_stabilization_s=cfg.disagg_stab_s,
                unified_stabilization_s=cfg.unified_stab_s,
                cooldown_s=cfg.cooldown_s,
                max_switch_cost_s=cfg.max_switch_cost_s),
            eval_period_s=cfg.eval_period_s, window_s=cfg.window_s,
            stale_after_s=cfg.stale_after_s,
            signals_fn=signals_fn, candidacy_fn=candidacy_fn)

    plane = ControlPlane(backend="fake", topology=topo_cfg)
    make_tpu_nodes(plane.store, slices=4, hosts_per_slice=4)

    def mk_role(name, replicas):
        role = simple_role(name, replicas=replicas)
        role.identity = IdentityMode.RANDOM
        role.drain_seconds = cfg.drain_s
        role.scaling_adapter = ScalingAdapterHook(
            enabled=True, min_replicas=0,
            max_replicas=max(cfg.unified_replicas, cfg.prefill_replicas
                             + cfg.decode_replicas))
        return role

    init = {
        gt.unified_role: cfg.unified_replicas if mode != "disagg" else 0,
        gt.prefill_role: cfg.prefill_replicas if mode == "disagg" else 0,
        gt.decode_role: cfg.decode_replicas if mode == "disagg" else 0,
    }
    roles = [mk_role(r, n) for r, n in init.items()]
    flips_before = {
        t: REGISTRY.counter(metric_names.TOPOLOGY_FLIPS_TOTAL,
                            group=group_name, target=t)
        for t in (POSTURE_UNIFIED, POSTURE_DISAGG)}

    t_run = time.perf_counter()
    plane.start()
    curve: List[dict] = []
    greens = [0]
    arrivals_total = [0]
    shed_total = [0]
    dropped = [0]
    completed = [0]
    flip_started_t: Optional[float] = None
    flip_done_t: Optional[float] = None
    phase2_t0 = cfg.duration_s * cfg.phase_fracs[0]
    try:
        plane.apply(make_group(group_name, *roles))
        plane.wait_group_ready(group_name, timeout=cfg.timeout_s)

        def pods():
            return [p for p in plane.store.list(
                "Pod", namespace="default",
                selector={C.LABEL_GROUP_NAME: group_name}) if p.active]

        def is_draining(p) -> bool:
            return (p.metadata.annotations.get(C2.ANN_LIFECYCLE_STATE)
                    == C2.LIFECYCLE_PREPARING_DELETE)

        def posture_now():
            g = plane.store.get("RoleBasedGroup", "default", group_name,
                                copy_=False)
            if g is None:
                return "?", ""
            a = g.metadata.annotations
            posture = a.get(C2.ANN_TOPOLOGY_POSTURE) or (
                POSTURE_UNIFIED if mode != "disagg" else POSTURE_DISAGG)
            return posture, a.get(C2.ANN_TOPOLOGY_STATE) or ""

        queue = collections.deque()      # (class_idx, t_arrive)
        streams: Dict[str, float] = {}
        carry = 0.0
        t0 = time.perf_counter()
        while True:
            now = time.perf_counter() - t0
            if now >= cfg.duration_s:
                break
            frac = now / cfg.duration_s
            phase = 0
            acc = 0.0
            for i, pf in enumerate(cfg.phase_fracs):
                acc += pf
                if frac < acc:
                    phase = i
                    break
            long_frac = cfg.long_frac_by_phase[phase]

            # ---- arrivals ----
            n_arr = _poisson(rng, cfg.rps * cfg.tick_s)
            for _ in range(n_arr):
                is_long = rng.random() < long_frac
                toks = cfg.long_tokens if is_long else cfg.chat_tokens
                arrivals_win.append((now, toks[0], toks[1]))
                queue.append((1 if is_long else 0, now))
                arrivals_total[0] += 1
            while arrivals_win and arrivals_win[0][0] < now - cfg.window_s:
                arrivals_win.popleft()
            while done_win and done_win[0] < now - cfg.window_s:
                done_win.popleft()

            # ---- pod census ----
            ps = pods()
            live = {p.metadata.name for p in ps}
            serving = [p for p in ps
                       if p.running_ready and not is_draining(p)
                       and p.metadata.labels.get(C.LABEL_ROLE_NAME)
                       in active_roles]
            draining = [p for p in ps if is_draining(p)]

            # ---- streams: vanished pods with streams are DROPS ----
            for pname in [n for n in streams if n not in live]:
                if streams[pname] > 0:
                    dropped[0] += int(streams[pname])
                del streams[pname]
            for p in draining:
                n = streams.get(p.metadata.name, 0.0)
                if n > 0:
                    streams[p.metadata.name] = max(0.0, n - 2.0)
                if streams.get(p.metadata.name, 0.0) <= 0:
                    iname = p.metadata.labels.get(C.LABEL_INSTANCE_NAME)
                    if iname:
                        def ack(i):
                            if i.metadata.annotations.get(
                                    C2.ANN_DRAIN_COMPLETE) == "true":
                                return False
                            i.metadata.annotations[
                                C2.ANN_DRAIN_COMPLETE] = "true"
                            return True
                        try:
                            plane.store.mutate("RoleInstance", "default",
                                               iname, ack)
                        except Exception:
                            pass
            want_streams = min(len(serving) * 4, int(cfg.rps / 6) + 1)
            have = sum(int(streams.get(p.metadata.name, 0.0))
                       for p in serving)
            for p in serving:
                if have >= want_streams:
                    break
                streams[p.metadata.name] = \
                    streams.get(p.metadata.name, 0.0) + 1
                have += 1
            streams_now = float(sum(streams.values()))

            # ---- service: capacity units complete the queue ----
            shape = ("disagg"
                     if gt.prefill_role in active_roles else "unified")
            costs = (cfg.cost_disagg if shape == "disagg"
                     else cfg.cost_unified)
            cap_units_s = len(serving) * cfg.per_replica_units
            units = carry + cap_units_s * cfg.tick_s
            while queue and units >= costs[queue[0][0]]:
                cls, t_arr = queue.popleft()
                units -= costs[cls]
                completed[0] += 1
                done_win.append(now)
                if now - t_arr <= cfg.slo_wait_s:
                    greens[0] += 1
            carry = min(units, cap_units_s * cfg.tick_s)
            while len(queue) > cfg.queue_limit:
                queue.pop()      # shed the newest — no capacity for it
                shed_total[0] += 1
            p_toks = sum(a[1] for a in arrivals_win)
            d_toks = sum(a[2] for a in arrivals_win)
            ratio_now = (round(p_toks / d_toks, 2)
                         if p_toks > 1e-9 and d_toks > 1e-9 else None)
            published["sig"] = {
                "fresh": True,
                "prefill_decode_ratio": ratio_now,
                "judged": len(done_win),
                "queue_depth": float(len(queue)),
                "kv_bytes_to_move": streams_now * cfg.kv_bytes_per_stream,
                "link_bytes_per_s": cfg.link_bytes_per_s,
            }
            sampler.sample_now()

            posture, state = posture_now()
            if mode == "adaptive":
                if flip_started_t is None and state:
                    flip_started_t = now
                if (flip_started_t is not None and flip_done_t is None
                        and posture == POSTURE_DISAGG and not state):
                    flip_done_t = now
            curve.append({
                "t": round(now, 3),
                "offered_rps": round(cfg.rps, 1),
                "long_frac": long_frac,
                "ratio": ratio_now,
                "posture": posture, "state": state,
                "capacity_units_s": round(cap_units_s, 1),
                "serving": len(serving),
                "queue": len(queue),
                "goodput_frac": round(
                    greens[0] / max(1, arrivals_total[0]), 4),
            })
            time.sleep(cfg.tick_s)
        status = (plane.topology_controller.status()
                  if plane.topology_controller else {})
    finally:
        plane.stop()

    flips = {
        t: round(REGISTRY.counter(metric_names.TOPOLOGY_FLIPS_TOTAL,
                                  group=group_name, target=t)
                 - flips_before[t], 1)
        for t in (POSTURE_UNIFIED, POSTURE_DISAGG)}
    goodput = greens[0] / max(1, arrivals_total[0])
    return {
        "mode": mode,
        "elapsed_s": round(time.perf_counter() - t_run, 3),
        "arrivals": arrivals_total[0],
        "completed": completed[0],
        "shed": shed_total[0],
        "greens": greens[0],
        "goodput_fraction": round(goodput, 4),
        "dropped_streams": dropped[0],
        "flips": flips,
        "flip_started_after_shift_s": (
            round(flip_started_t - phase2_t0, 3)
            if flip_started_t is not None else None),
        "flip_done_after_shift_s": (
            round(flip_done_t - phase2_t0, 3)
            if flip_done_t is not None else None),
        "end_posture": curve[-1]["posture"] if curve else "?",
        "topology_status": status,
        "curve": curve,
    }


def _topoflip_token_exact(cfg: TopoFlipConfig) -> dict:
    """Real-engine leg: an in-flight PD stream cut mid-transfer (what a
    drained old-shape backend does to its stream at cutover) must finish
    token-exact through the PR-10 bundle fallback — outputs bit-identical
    to a unified engine."""
    import numpy as np

    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.engine import Engine
    from rbg_tpu.engine.pd import PDStreamPair
    from rbg_tpu.kvtransfer import InProcTransport, SlowLossyTransport

    page_size = 8
    ecfg = dict(model="tiny", page_size=page_size, num_pages=128,
                max_batch=2, max_seq_len=128, prefill_chunk=16,
                use_pallas="never")
    rng = np.random.RandomState(23)
    eng_ref = Engine(EngineConfig(enable_radix_cache=False, **ecfg))
    vocab = eng_ref.mcfg.vocab_size
    prompts = [rng.randint(1, vocab, size=40).tolist() for _ in range(2)]
    sp = SamplingParams(max_new_tokens=6)
    expect = eng_ref.generate(prompts, sp)

    link = SlowLossyTransport(InProcTransport(), delay_s=0.002,
                              truncate_nth_stream=1,
                              truncate_after_bytes=1 << 11, seed=5)
    pair = PDStreamPair(EngineConfig(**ecfg), params=eng_ref.params,
                        transport=link)
    results, retries, failures = [], 0, []
    for i, p in enumerate(prompts):
        try:
            r = pair.generate_one(p, sp, stream=True, recv_timeout=60.0,
                                  max_retries=2)
            retries += r["retries"]
            results.append(r)
        except Exception as e:  # noqa: BLE001 — account, don't crash
            failures.append(f"request {i}: {type(e).__name__}: {e}")
            results.append(None)
    bit_identical = all(r is not None and r["tokens"] == e
                        for r, e in zip(results, expect))
    return {"requests": len(prompts), "stream_retries": retries,
            "failures": failures, "bit_identical": bit_identical}


def run_topoflip(cfg: TopoFlipConfig) -> dict:
    t_run = time.perf_counter()
    converge_bound = (cfg.window_s + cfg.disagg_stab_s
                      + 2 * cfg.eval_period_s + 0.75)

    def one_attempt(attempt: int) -> dict:
        reps: Dict[str, List[dict]] = {
            "adaptive": [], "static_unified": [], "static_disagg": []}
        for _ in range(max(1, cfg.reps)):
            # Strict interleave: every adaptive rep has adjacent static
            # reps in the same machine regime (ROADMAP: throughput here
            # is bimodal at multi-second granularity).
            reps["adaptive"].append(_run_topoflip_rep(cfg, "adaptive"))
            reps["static_unified"].append(_run_topoflip_rep(cfg, "unified"))
            reps["static_disagg"].append(_run_topoflip_rep(cfg, "disagg"))
        med = {m: _median([r["goodput_fraction"] for r in rs])
               for m, rs in reps.items()}
        spread = max(_trimmed_spread([r["goodput_fraction"] for r in rs])
                     for rs in reps.values())
        ad = reps["adaptive"]
        out = {
            "attempt": attempt,
            "reps": reps,
            "median_goodput": med,
            "spread": round(spread, 4),
            "spread_max": cfg.spread_max,
            "spread_estimator": "trimmed_minmax_drop1",
            "converge_bound_s": round(converge_bound, 3),
            "dropped_streams": sum(r["dropped_streams"]
                                   for rs in reps.values() for r in rs),
            "converged": all(
                r["flip_started_after_shift_s"] is not None
                and r["flip_started_after_shift_s"] <= converge_bound
                and r["end_posture"] == "disagg" for r in ad),
            "flap_bounded": all(
                sum(r["flips"].values()) <= cfg.max_flips for r in ad),
            "goodput_ge_static": med["adaptive"] >= max(
                med["static_unified"], med["static_disagg"]),
            "spread_ok": spread <= cfg.spread_max,
        }
        return out

    last = None
    for attempt in range(1, max(1, cfg.attempts) + 1):
        last = one_attempt(attempt)
        if (last["converged"] and last["flap_bounded"]
                and last["dropped_streams"] == 0
                and (cfg.reps < 2
                     or (last["goodput_ge_static"] and last["spread_ok"]))):
            break

    token_exact = _topoflip_token_exact(cfg) if cfg.token_exact else None
    inv: Dict[str, bool] = {
        "zero_dropped_streams": last["dropped_streams"] == 0,
        "topology_converged": last["converged"],
        "no_flap": last["flap_bounded"],
    }
    if token_exact is not None:
        # The cut stream was retried through the bundle fallback, nothing
        # was dropped, outputs match the unified engine bit-for-bit.
        inv["bit_identical"] = (token_exact["bit_identical"]
                                and not token_exact["failures"]
                                and token_exact["stream_retries"] >= 1)
    if cfg.reps >= 2:
        # The headline gate needs interleaved reps to mean anything; a
        # single-rep smoke run reports the comparison without gating it.
        inv["goodput_adaptive_ge_static"] = bool(
            last["goodput_ge_static"])
        inv["goodput_spread_ok"] = bool(last["spread_ok"])
    curve = (last["reps"]["adaptive"][0]["curve"]
             if last["reps"]["adaptive"] else [])
    report = {
        "scenario": "topoflip",
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(time.perf_counter() - t_run, 3),
        **{k: v for k, v in last.items() if k != "reps"},
        "reps": {
            m: [{k: v for k, v in r.items()
                 if k not in ("curve", "topology_status")} for r in rs]
            for m, rs in last["reps"].items()},
        "topology_status_end": (
            last["reps"]["adaptive"][0].get("topology_status")
            if last["reps"]["adaptive"] else {}),
        "curve": curve,
        "token_exact": token_exact,
        "invariants": inv,
    }
    return report


# ---- slice preemption / self-healing scenario ------------------------------


@dataclasses.dataclass
class PreemptionConfig:
    """Slice disruption drill: no-notice partial preemption (gang
    semantics), advance-notice maintenance migration (deadline), and the
    serving-plane cutover legs (router replay mid-stream, rolling drain).
    The report carries the self-healing invariants the disruption
    subsystem promises."""

    groups: int = 2
    slices: int = 6
    hosts_per_slice: int = 2
    warm_spares: int = 1
    notice_deadline_s: float = 25.0
    timeout_s: float = 60.0
    stream_tokens: int = 12


def _counters_snapshot() -> Dict[str, float]:
    from rbg_tpu.runtime.controllers.disruption import DISRUPTION_COUNTERS
    return {name: REGISTRY.counter(name) for name in DISRUPTION_COUNTERS}


def run_preemption(cfg: PreemptionConfig) -> dict:
    """Drive the full disruption lifecycle against a fake fleet and a
    scripted serving plane, asserting the invariants:

    * zero partial-slice survivors after a no-notice preemption — the
      whole gang fails and reconverges on ONE healthy slice;
    * an advance-notice migration releases the slice BEFORE its deadline
      and the group reconverges;
    * an in-flight stream whose backend dies mid-stream finishes via
      router replay with no dropped or duplicated tokens;
    * when EVERY backend of a role drains at once, requests get a
      structured retriable error carrying the smallest retry_after_s —
      never a hang or a dropped stream;
    * ``rbg_disruption_*`` counters reflect the run.
    """
    from rbg_tpu.api.group import RestartPolicyConfig
    from rbg_tpu.runtime.controllers.disruption import (
        notify_maintenance, preempt_slice,
    )
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.testutil import tpu_leaderworker_role

    before = _counters_snapshot()
    t_run = time.perf_counter()
    plane = ControlPlane(backend="fake", warm_spares=cfg.warm_spares)
    make_tpu_nodes(plane.store, slices=cfg.slices,
                   hosts_per_slice=cfg.hosts_per_slice)
    inv: Dict[str, bool] = {}
    phases: Dict[str, float] = {}

    def gang_pods(group):
        return [p for p in plane.store.list("Pod", namespace="default")
                if p.metadata.labels.get(C.LABEL_GROUP_NAME) == group
                and p.active]

    def gang_slices(group):
        nodes = {n.metadata.name: n for n in plane.store.list("Node")}
        return {nodes[p.node_name].tpu.slice_id
                for p in gang_pods(group) if p.node_name}

    plane.start()
    try:
        for i in range(cfg.groups):
            role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
            role.restart_policy = RestartPolicyConfig(
                base_delay_seconds=0.01, max_delay_seconds=0.1)
            plane.apply(make_group(f"prm-{i}", role))
        for i in range(cfg.groups):
            plane.wait_group_ready(f"prm-{i}", timeout=cfg.timeout_s)

        # ---- phase A: no-notice partial preemption (gang semantics) ----
        g0 = "prm-0"
        old_slice = gang_slices(g0).pop()
        old_uids = {p.metadata.uid for p in gang_pods(g0)}
        gang_n = len(old_uids)  # gang size = hosts of ONE slice replica
        victim = sorted(p.node_name for p in gang_pods(g0))[0]
        t0 = time.perf_counter()
        preempt_slice(plane.store, old_slice, hosts=[victim])

        def recovered():
            ps = gang_pods(g0)
            return (len(ps) == gang_n
                    and old_uids.isdisjoint({p.metadata.uid for p in ps})
                    and all(p.running_ready and p.node_name for p in ps))

        try:
            plane.wait_for(recovered, timeout=cfg.timeout_s,
                           desc="gang recovered")
            phases["preempt_recover_s"] = round(time.perf_counter() - t0, 3)
            slices_now = gang_slices(g0)
            nodes = {n.metadata.name: n for n in plane.store.list("Node")}
            survivors = [p for p in plane.store.list("Pod",
                                                     namespace="default")
                         if p.active and p.node_name
                         and nodes[p.node_name].tpu.slice_id == old_slice]
            inv["no_partial_slice_survivors"] = (
                not survivors and len(slices_now) == 1
                and old_slice not in slices_now)
            plane.wait_group_ready(g0, timeout=cfg.timeout_s)
            inv["group_reconverged_after_preemption"] = True
        except TimeoutError:
            inv["no_partial_slice_survivors"] = False
            inv["group_reconverged_after_preemption"] = False

        # ---- phase B: advance-notice maintenance migration ----
        g1 = f"prm-{min(1, cfg.groups - 1)}"
        maint_slice = gang_slices(g1).pop()
        gang_n1 = len(gang_pods(g1))
        t0 = time.perf_counter()
        notify_maintenance(plane.store, maint_slice, cfg.notice_deadline_s)

        def released():
            ns = [n for n in plane.store.list("Node")
                  if n.tpu.slice_id == maint_slice]
            return ns and all(
                n.metadata.annotations.get(C.ANN_MAINT_RELEASED) for n in ns)

        try:
            plane.wait_for(released, timeout=cfg.notice_deadline_s,
                           desc="slice released")
            phases["migration_release_s"] = round(time.perf_counter() - t0, 3)
            inv["released_before_deadline"] = (
                phases["migration_release_s"] < cfg.notice_deadline_s)

            def serving():
                ps = gang_pods(g1)
                return (len(ps) == gang_n1
                        and all(p.running_ready and p.node_name for p in ps))

            plane.wait_for(serving, timeout=cfg.timeout_s,
                           desc="migrated gang serving")
            plane.wait_group_ready(g1, timeout=cfg.timeout_s)
            inv["group_reconverged_after_migration"] = (
                gang_slices(g1) != {maint_slice})

            def unwound():
                return all(
                    C.ANN_MIGRATION_STATE not in i.metadata.annotations
                    for i in plane.store.list("RoleInstance",
                                              namespace="default"))

            # The completion pass (annotation clear + counter) lands one
            # reconcile after the gang turns ready — wait for it so the
            # counter invariant below observes the finished run, not a
            # plane stopped mid-bookkeeping.
            plane.wait_for(unwound, timeout=cfg.timeout_s,
                           desc="migration bookkeeping unwound")
        except TimeoutError:
            inv.setdefault("released_before_deadline", False)
            inv["group_reconverged_after_migration"] = False
    finally:
        plane.stop()

    # ---- phase C: serving-plane cutover (router replay + rolling drain) ----
    replay = _router_replay_drill(cfg.stream_tokens)
    inv["stream_survived_backend_death"] = replay["stream_ok"]
    inv["rolling_drain_structured_error"] = replay["drain_ok"]
    # slo_accounted at the ROUTER vantage: exactly the one stream that
    # finished was judged (the drained request was refused, never
    # finished, never judged) — and the failed-over stream's TTFT was
    # measured from ingress, so the judgment survived the mid-stream
    # backend death.
    slo = replay.get("slo") or {}
    inv["slo_accounted"] = slo.get("judged") == 1
    phases["router_replay"] = replay

    after = _counters_snapshot()
    deltas = {k: round(after[k] - before.get(k, 0.0), 1) for k in after}
    inv["disruption_counters_moved"] = (
        deltas.get(metric_names.DISRUPTION_PREEMPTIONS_TOTAL, 0) >= 1
        and deltas.get(metric_names.DISRUPTION_GANG_KILLS_TOTAL, 0) >= 1
        and deltas.get(metric_names.DISRUPTION_NOTICES_TOTAL, 0) >= 1
        and deltas.get(metric_names.DISRUPTION_MIGRATIONS_COMPLETED_TOTAL, 0) >= 1
        and deltas.get(metric_names.DISRUPTION_MIGRATIONS_MISSED_DEADLINE_TOTAL,
                       0) == 0)
    return {
        "scenario": "preemption",
        "config": dataclasses.asdict(cfg),
        "elapsed_s": round(time.perf_counter() - t_run, 3),
        "phases": phases,
        "disruption_counters": deltas,
        # Per-topology reserved-spare counts straight from the pool (the
        # gauge's topology label depends on the fleet shape — never
        # hardcode it).
        "spare_pool_depth": plane.spares.depth(),
        # Router-vantage SLO attainment for the serving-plane legs.
        "slo": slo,
        "invariants": inv,
    }


def _router_replay_drill(n_tokens: int) -> dict:
    """In-process serving-plane legs of the preemption drill, scripted so
    they are deterministic: (1) a streaming request whose backend is
    killed mid-stream must complete via the router's deterministic replay
    with the token sequence intact; (2) with EVERY backend of the role
    draining (rolling preemption), a request must return a structured
    retriable error carrying the smallest retry_after_s."""
    import socketserver

    from rbg_tpu.api.ops import OP_GENERATE, OP_HEALTH
    from rbg_tpu.engine.protocol import (CODE_DRAINING, recv_msg,
                                         request_once, send_msg)
    from rbg_tpu.engine.router import (Handler, Registry, RouterServer,
                                       RouterState)

    class ScriptedBackend(socketserver.ThreadingTCPServer):
        """Streams tokens 0..n-1 one frame at a time; can be told to die
        mid-stream once, or to shed everything as draining."""

        allow_reuse_address = True
        daemon_threads = True

        def __init__(self, die_after: Optional[int] = None,
                     retry_after_s: Optional[float] = None):
            backend = self
            backend.die_after = die_after
            backend.draining = False
            backend.retry_after_s = retry_after_s
            backend.serve_count = 0

            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    while True:
                        try:
                            obj, _, _ = recv_msg(self.request)
                        except (ConnectionError, json.JSONDecodeError):
                            return
                        if obj is None:
                            return
                        if obj.get("op") == OP_HEALTH:
                            send_msg(self.request,
                                     {"ok": True,
                                      "draining": backend.draining})
                            continue
                        if backend.draining:
                            frame = {"error": "backend is draining",
                                     "code": CODE_DRAINING, "done": True}
                            if backend.retry_after_s is not None:
                                frame["retry_after_s"] = backend.retry_after_s
                            send_msg(self.request, frame)
                            continue
                        backend.serve_count += 1
                        die_at = backend.die_after
                        backend.die_after = None  # die once, then serve
                        for t in range(n_tokens):
                            if die_at is not None and t == die_at:
                                return  # mid-stream death: cut the socket
                            send_msg(self.request,
                                     {"tokens": [t], "done": False})
                            time.sleep(0.01)
                        send_msg(self.request, {"tokens": [], "done": True})

            super().__init__(("127.0.0.1", 0), H)
            self.addr = f"127.0.0.1:{self.server_address[1]}"
            import threading
            threading.Thread(target=self.serve_forever, daemon=True).start()

    from rbg_tpu.obs.slo import SLOTargets

    flaky = ScriptedBackend(die_after=max(1, n_tokens // 3),
                            retry_after_s=3.0)
    steady = ScriptedBackend(retry_after_s=1.5)
    router = RouterServer(("127.0.0.1", 0), Handler)
    # Targets sized to the scripted stream (10 ms/token): the surviving
    # replayed stream should JUDGE, and judge green — the drill asserts
    # accounting, the attainment numbers land in the report.
    router.state = RouterState(Registry(None), None,
                               {"worker": [flaky.addr, steady.addr]},
                               slo_targets=SLOTargets(ttft_s=10.0,
                                                      tpot_s=1.0))
    import threading
    threading.Thread(target=router.serve_forever, daemon=True).start()
    router_addr = f"127.0.0.1:{router.server_address[1]}"
    out = {"stream_ok": False, "drain_ok": False}
    try:
        # Leg 1: stream with a mid-stream backend death → replay must
        # deliver 0..n-1 exactly once (the flaky backend dies first only
        # if it is picked first; force it by loading the steady one).
        import socket as _socket
        router.state.pool.acquire(steady.addr)
        got: List[int] = []
        host, port = router_addr.rsplit(":", 1)
        with _socket.create_connection((host, int(port)), timeout=10) as s:
            send_msg(s, {"op": OP_GENERATE, "stream": True,
                         "prompt": [1, 2, 3], "timeout_s": 20})
            while True:
                frame, _, _ = recv_msg(s)
                if frame is None or "error" in frame:
                    break
                got.extend(frame.get("tokens") or [])
                if frame.get("done"):
                    out["stream_ok"] = (got == list(range(n_tokens)))
                    break
        router.state.pool.release(steady.addr)

        # Leg 2: rolling preemption — EVERY backend draining at once.
        flaky.draining = True
        steady.draining = True
        resp, _, _ = request_once(
            router_addr,
            {"op": OP_GENERATE, "prompt": [1], "timeout_s": 5}, timeout=10)
        out["drain_ok"] = (resp is not None
                          and resp.get("code") == CODE_DRAINING
                          and resp.get("retry_after_s") == 1.5)
        out["drain_reply"] = resp
        out["slo"] = {
            "targets": router.state.slo.targets.as_dict(),
            "judged": router.state.slo.judged_total(),
            "per_role": router.state.slo.attainment(60.0,
                                                    group_by=("role",)),
            "per_backend": router.state.slo.attainment(
                60.0, group_by=("backend",)),
        }
    finally:
        router.shutdown()
        flaky.shutdown()
        steady.shutdown()
    return out


# ---- HA scenario: kill the leader, kill a router ---------------------------


@dataclasses.dataclass
class HAConfig:
    """The two-SPOF drill. Leg A (plane HA): two lease-campaigning
    ``LeaderElector`` candidates over ONE store; the leader dies while a
    PR-3 migration AND a PR-13 topology flip are mid-state-machine; the
    standby must take the lease, resume BOTH annotation-carried machines
    from the store, and the deposed leader's replayed in-flight writes
    must be refused by the epoch fence — zero double-actuation. A live
    SSE-style stream spans the failover untouched (the data plane does
    not ride the control plane). Leg B (router tier): a hash-ring tier
    of N routers serving token streams loses one member mid-stream; its
    sessions re-hash to ring successors and replay token-exact (pinned
    seed + delivered-prefix skip) while sessions on other members see
    no re-route at all. Leg C: the topology ratio signal computed from
    the tier aggregate is IDENTICAL whether the same trace feeds 1
    router or N."""

    routers: int = 3
    sessions: int = 24
    stream_tokens: int = 48
    ttl_s: float = 0.6
    renew_period_s: float = 0.15
    ready_delay_s: float = 1.5
    flip_drain_s: float = 30.0       # gate: A must NOT finish the flip
    notice_deadline_s: float = 25.0
    timeout_s: float = 60.0
    seed: int = 17


def run_ha(cfg: HAConfig) -> dict:
    report: Dict[str, object] = {"scenario": "ha",
                                 "config": dataclasses.asdict(cfg)}
    inv: Dict[str, bool] = {}
    t_run = time.perf_counter()
    report["plane_ha"] = _ha_leader_drill(cfg, inv)
    report["router_kill"] = _ha_router_kill_drill(cfg, inv)
    report["ratio_identity"] = _ha_ratio_identity(cfg, inv)
    report["elapsed_s"] = round(time.perf_counter() - t_run, 3)
    report["invariants"] = inv
    return report


def _ha_leader_drill(cfg: HAConfig, inv: Dict[str, bool]) -> dict:
    from rbg_tpu.api.group import (IdentityMode, RestartPolicyConfig,
                                   ScalingAdapterHook)
    from rbg_tpu.runtime.controllers.disruption import notify_maintenance
    from rbg_tpu.runtime.ha import LeaderElector
    from rbg_tpu.runtime.store import LeaseFenced, Store
    from rbg_tpu.testutil import tpu_leaderworker_role
    from rbg_tpu.topology import (GroupTopology, POSTURE_DISAGG,
                                  TopologyConfig, TopologyPolicyConfig)

    out: Dict[str, object] = {}
    store = Store()
    make_tpu_nodes(store, slices=4, hosts_per_slice=2)

    # Forced-ratio slot: the drill flips the signal to disagg pressure at
    # a scripted moment (one-slot publish, the topoflip pattern).
    sig = {"cur": {"fresh": True, "prefill_decode_ratio": 1.0,
                   "judged": 10, "link_bytes_per_s": 1e9}}
    flip_group = "ha-flip"
    gt = GroupTopology(group=flip_group, unified_replicas=2,
                       prefill_replicas=1, decode_replicas=1)
    topo_cfg = TopologyConfig(
        groups=[gt],
        policy=TopologyPolicyConfig(
            disagg_ratio=6.0, unified_ratio=2.0, min_judged=3,
            disagg_stabilization_s=0.1, unified_stabilization_s=0.1,
            cooldown_s=0.5, max_switch_cost_s=60.0),
        eval_period_s=0.1, window_s=5.0, stale_after_s=30.0,
        signals_fn=lambda _gt: dict(sig["cur"]))

    def plane_factory(fenced):
        # Fresh plane per leadership TERM, reading ONLY the store: this
        # is what makes takeover a restart-resume drill.
        return ControlPlane(store=fenced, backend="fake",
                            ready_delay=cfg.ready_delay_s, warm_spares=1,
                            topology=topo_cfg)

    def mk_flip_role(name, replicas):
        role = simple_role(name, replicas=replicas)
        role.identity = IdentityMode.RANDOM
        role.drain_seconds = cfg.flip_drain_s
        role.scaling_adapter = ScalingAdapterHook(enabled=True,
                                                 min_replicas=0,
                                                 max_replicas=4)
        return role

    fenced_before = REGISTRY.counter(
        metric_names.PLANE_FENCED_WRITES_TOTAL,
        lease="control-plane")
    flips_before = REGISTRY.counter(metric_names.TOPOLOGY_FLIPS_TOTAL,
                                    group=flip_group,
                                    target=POSTURE_DISAGG)
    mig_before = REGISTRY.counter(
        metric_names.DISRUPTION_MIGRATIONS_COMPLETED_TOTAL)

    el_a = LeaderElector("plane-a", store, plane_factory,
                         ttl_s=cfg.ttl_s,
                         renew_period_s=cfg.renew_period_s)
    el_b = LeaderElector("plane-b", store, plane_factory,
                         ttl_s=cfg.ttl_s,
                         renew_period_s=cfg.renew_period_s)
    stream = {"tokens": [], "ok": False}
    stream_thread = None
    backends = []
    try:
        el_a.start()
        _wait(lambda: el_a.is_leader, cfg.timeout_s, "A leads")
        el_b.start()          # standby: campaigns, loses, tails the watch
        plane_a = el_a.plane

        # Migration target: one TPU gang on a slice; flip target: the
        # topology-managed group starting unified.
        mig_group = "ha-mig"
        role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
        role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.01,
                                                  max_delay_seconds=0.1)
        plane_a.apply(make_group(mig_group, role))
        plane_a.apply(make_group(flip_group, *[
            mk_flip_role(r, n) for r, n in
            ((gt.unified_role, 2), (gt.prefill_role, 0),
             (gt.decode_role, 0))]))
        plane_a.wait_group_ready(mig_group, timeout=cfg.timeout_s)
        plane_a.wait_group_ready(flip_group, timeout=cfg.timeout_s)

        # A live stream spanning the failover: data plane vs control
        # plane separation made measurable.
        stream_thread, backends = _ha_background_stream(
            stream, n_tokens=cfg.stream_tokens)

        # ---- wound both state machines ----
        def gang_slice():
            nodes = {n.metadata.name: n for n in store.list("Node")}
            for p in store.list("Pod", namespace="default"):
                if (p.metadata.labels.get(C.LABEL_GROUP_NAME) == mig_group
                        and p.active and p.node_name):
                    return nodes[p.node_name].tpu.slice_id
            return None

        notify_maintenance(store, gang_slice(), cfg.notice_deadline_s)
        sig["cur"] = {"fresh": True, "prefill_decode_ratio": 20.0,
                      "judged": 10, "link_bytes_per_s": 1e9}

        def mid_migration():
            return any(C.ANN_MIGRATION_STATE in i.metadata.annotations
                       for i in store.list("RoleInstance",
                                           namespace="default"))

        def flip_state():
            g = store.get("RoleBasedGroup", "default", flip_group,
                          copy_=False)
            return (g.metadata.annotations.get(C.ANN_TOPOLOGY_STATE) or ""
                    if g is not None else "")

        _wait(mid_migration, cfg.timeout_s, "migration mid-machine")
        _wait(flip_state, cfg.timeout_s, "flip mid-machine")

        # ---- kill the leader (no lease release: crash, not shutdown) --
        el_a.kill()
        deposed = el_a.fenced_store
        out["mid_state_at_kill"] = {"migration": mid_migration(),
                                    "flip": flip_state()}
        _wait(lambda: el_b.is_leader, cfg.ttl_s * 4 + 5.0, "B takes over")
        out["mid_state_at_takeover"] = {"migration": mid_migration(),
                                        "flip": flip_state()}
        inv["standby_resumed_mid_state"] = (
            out["mid_state_at_takeover"]["migration"]
            and bool(out["mid_state_at_takeover"]["flip"]))

        # ---- the deposed leader replays its in-flight writes ----
        refusals = 0
        marker = "stress.rbg.io/deposed-write"

        def poison(g):
            g.metadata.annotations[marker] = "1"
            return True

        for fn in (poison, lambda g: False):   # real write AND no-op path
            try:
                deposed.mutate("RoleBasedGroup", "default", flip_group, fn)
            except LeaseFenced:
                refusals += 1
        g_now = store.get("RoleBasedGroup", "default", flip_group)
        out["fence_refusals"] = refusals
        inv["deposed_writes_fenced"] = (
            refusals == 2
            and marker not in g_now.metadata.annotations
            and REGISTRY.counter(metric_names.PLANE_FENCED_WRITES_TOTAL,
                                 lease="control-plane")
            - fenced_before >= 2)

        # ---- standby completes BOTH machines ----
        # The flip's Draining phase is gated on drain acks the dead
        # leader never got (drain_seconds ≫ drill): ack them under B,
        # like a serving plane finishing its streams.
        def ack_drains():
            for i in store.list("RoleInstance", namespace="default"):
                a = i.metadata.annotations
                if (a.get(C.ANN_LIFECYCLE_STATE)
                        == C.LIFECYCLE_PREPARING_DELETE
                        and a.get(C.ANN_DRAIN_COMPLETE) != "true"):
                    def ack(obj):
                        if obj.metadata.annotations.get(
                                C.ANN_DRAIN_COMPLETE) == "true":
                            return False
                        obj.metadata.annotations[
                            C.ANN_DRAIN_COMPLETE] = "true"
                        return True
                    try:
                        store.mutate("RoleInstance", "default",
                                     i.metadata.name, ack)
                    except Exception:
                        pass

        def flip_done():
            ack_drains()
            g = store.get("RoleBasedGroup", "default", flip_group,
                          copy_=False)
            a = g.metadata.annotations
            return (not a.get(C.ANN_TOPOLOGY_STATE)
                    and a.get(C.ANN_TOPOLOGY_POSTURE) == POSTURE_DISAGG)

        def migration_done():
            return not mid_migration()

        t0 = time.perf_counter()
        _wait(flip_done, cfg.timeout_s, "flip completed by standby")
        _wait(migration_done, cfg.timeout_s,
              "migration completed by standby")
        el_b.plane.wait_group_ready(mig_group, timeout=cfg.timeout_s)
        out["resume_complete_s"] = round(time.perf_counter() - t0, 3)
        inv["migration_completed_by_standby"] = True
        inv["flip_completed_by_standby"] = True
    except TimeoutError as e:
        out["timeout"] = str(e)
        inv.setdefault("standby_resumed_mid_state", False)
        inv.setdefault("deposed_writes_fenced", False)
        inv.setdefault("migration_completed_by_standby", False)
        inv.setdefault("flip_completed_by_standby", False)
    finally:
        if stream_thread is not None:
            stream_thread.join(timeout=30.0)
        for b in backends:
            b.shutdown()
        el_b.stop()
        el_a.stop()

    inv["leader_failover_completed"] = bool(el_b.is_leader is False
                                            and el_b.transitions >= 1)
    # Exactly-once actuation: ONE flip, ONE migration, across both terms.
    flips = REGISTRY.counter(metric_names.TOPOLOGY_FLIPS_TOTAL,
                             group=flip_group,
                             target=POSTURE_DISAGG) - flips_before
    migs = REGISTRY.counter(
        metric_names.DISRUPTION_MIGRATIONS_COMPLETED_TOTAL) - mig_before
    out["flips"] = round(flips, 1)
    out["migrations_completed"] = round(migs, 1)
    inv["no_double_actuation"] = (flips == 1.0 and migs == 1.0)
    inv["zero_dropped_streams_plane"] = stream["ok"]
    out["electors"] = [el_a.snapshot(), el_b.snapshot()]
    out["stream_tokens_delivered"] = len(stream["tokens"])
    return out


def _wait(fn, timeout_s: float, desc: str, interval: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            if fn():
                return
        except Exception:
            pass
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}")


def _ha_background_stream(slot: dict, n_tokens: int):
    """One real router+backend token stream paced to SPAN the leader
    failover (~40 ms/token): started before the kill, asserted after the
    standby finishes — the control plane's death must not cost the data
    plane a single frame."""
    import socket as _socket
    import socketserver
    import threading

    from rbg_tpu.api.ops import OP_GENERATE, OP_HEALTH
    from rbg_tpu.engine.protocol import recv_msg, send_msg
    from rbg_tpu.engine.router import (Handler, Registry, RouterServer,
                                       RouterState)

    class SlowBackend(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self):
            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    while True:
                        try:
                            obj, _, _ = recv_msg(self.request)
                        except (ConnectionError, json.JSONDecodeError):
                            return
                        if obj is None:
                            return
                        if obj.get("op") == OP_HEALTH:
                            send_msg(self.request, {"ok": True})
                            continue
                        for t in range(n_tokens):
                            send_msg(self.request,
                                     {"tokens": [t], "done": False})
                            time.sleep(0.04)
                        send_msg(self.request, {"tokens": [], "done": True})

            super().__init__(("127.0.0.1", 0), H)
            self.addr = f"127.0.0.1:{self.server_address[1]}"
            threading.Thread(target=self.serve_forever,
                             daemon=True).start()

    backend = SlowBackend()
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None,
                               {"worker": [backend.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    router_addr = f"127.0.0.1:{router.server_address[1]}"

    def run():
        host, port = router_addr.rsplit(":", 1)
        try:
            with _socket.create_connection((host, int(port)),
                                           timeout=30) as s:
                send_msg(s, {"op": OP_GENERATE, "stream": True,
                             "prompt": [1, 2, 3], "timeout_s": 60})
                while True:
                    frame, _, _ = recv_msg(s)
                    if frame is None or "error" in frame:
                        return
                    slot["tokens"].extend(frame.get("tokens") or [])
                    if frame.get("done"):
                        slot["ok"] = (slot["tokens"]
                                      == list(range(n_tokens)))
                        return
        except OSError:
            return

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, [router, backend]


def _ha_router_kill_drill(cfg: HAConfig, inv: Dict[str, bool]) -> dict:
    """Kill one of N tier routers while every session is mid-stream:
    its sessions re-hash to ring successors and replay token-exact;
    sessions owned by surviving members never re-route."""
    import threading

    from rbg_tpu.engine.routertier import MemberDown, RouterTier, TierClient

    tier = RouterTier(name="stress-ha")
    names = [f"rtr-{i}" for i in range(cfg.routers)]
    for n in names:
        tier.register(n)
    killed: set = set()
    kill_done = threading.Event()

    def token_fn(seed: int, pos: int) -> int:
        return (seed * 1315423911 + pos * 2654435761) & 0xFFFF

    half = cfg.stream_tokens // 2

    def deliver(member, key, seed, start, n):
        # Every session parks at its stream midpoint until the victim is
        # dead: the kill lands while ALL sessions are provably
        # mid-stream, so the drill is deterministic, not a sleep race.
        if start >= half:
            kill_done.wait(timeout=10.0)
        time.sleep(0.001)
        if member in killed or member not in tier.ring:
            raise MemberDown(member)
        return [token_fn(seed, p) for p in range(start, start + n)]

    client = TierClient(tier, token_fn, deliver_fn=deliver)
    rng = __import__("random").Random(cfg.seed)
    sessions = [(f"sess-{i}", rng.getrandbits(31))
                for i in range(cfg.sessions)]
    # Kill the ring owner of the most sessions (bounded-load may spill a
    # few at runtime; classification below is by ACTUAL serving member).
    owner_at_start = {k: tier.ring.owner(k) for k, _ in sessions}
    victim = max(set(owner_at_start.values()),
                 key=lambda m: sum(1 for v in owner_at_start.values()
                                   if v == m))
    results: Dict[str, dict] = {}
    errors: List[str] = []

    def run_one(key, seed):
        try:
            results[key] = client.run_session(key, seed,
                                              cfg.stream_tokens, chunk=4)
        except Exception as e:  # noqa: BLE001
            errors.append(f"{key}: {e}")

    threads = [threading.Thread(target=run_one, args=s, daemon=True)
               for s in sessions]
    for t in threads:
        t.start()
    time.sleep(0.05)          # let every session reach the midpoint park
    killed.add(victim)
    tier.remove(victim)       # the crash: hash ranges move to successors
    kill_done.set()
    for t in threads:
        t.join(timeout=30.0)

    reference = {k: [token_fn(seed, p) for p in range(cfg.stream_tokens)]
                 for k, seed in sessions}
    exact = all(results.get(k, {}).get("tokens") == reference[k]
                for k, _ in sessions)
    affected = [k for k, _ in sessions
                if victim in results.get(k, {}).get("members", [])]
    untouched = [k for k, _ in sessions if k not in affected]
    undisturbed = all(
        results.get(k, {}).get("rehashes", 1) == 0
        and len(results.get(k, {}).get("members", [])) == 1
        for k in untouched)
    rehashed = all(
        results.get(k, {}).get("rehashes", 0) >= 1
        and results.get(k, {}).get("members", [None])[-1] != victim
        for k in affected)
    inv["router_kill_token_exact"] = exact and not errors
    inv["affected_sessions_rehash"] = bool(affected) and rehashed
    inv["untouched_sessions_undisturbed"] = bool(untouched) and undisturbed
    inv["zero_dropped_streams_tier"] = (not errors
                                        and len(results) == len(sessions))
    return {
        "victim": victim,
        "sessions": len(sessions),
        "affected": len(affected),
        "untouched": len(untouched),
        "rehashes": client.rehashes,
        "errors": errors[:5],
        "ring_after": tier.members(),
    }


def _ha_ratio_identity(cfg: HAConfig, inv: Dict[str, bool]) -> dict:
    """The aggregation contract, proven: the SAME ingress trace fed to a
    1-router tier and an N-router tier (sessions split by ring ownership)
    yields the IDENTICAL prefill:decode ratio — because the ratio is
    taken over tier SUMS, never per-member ratios."""
    from rbg_tpu.engine.routertier import RouterTier
    from rbg_tpu.topology.signals import tier_ingress_ratio

    clock = {"t": 1000.0}
    tick = lambda: clock["t"]  # noqa: E731
    one = RouterTier(name="one", clock=tick)
    one.register("solo")
    many = RouterTier(name="many", clock=tick)
    names = [f"r{i}" for i in range(cfg.routers)]
    for n in names:
        many.register(n)

    rng = __import__("random").Random(cfg.seed + 1)
    for i in range(400):
        clock["t"] += 0.05
        key = f"sess-{rng.randrange(64)}"
        prompt = rng.choice((32, 64, 2048))
        decode = rng.choice((16, 64, 128))
        one.note_ingress("solo", "prefill", prompt)
        one.note_ingress("solo", "decode", decode)
        member = many.route(key) or names[0]
        many.note_ingress(member, "prefill", prompt)
        many.note_ingress(member, "decode", decode)

    now = clock["t"]
    r1 = tier_ingress_ratio(one, window_s=60.0, now=now)
    rn = tier_ingress_ratio(many, window_s=60.0, now=now)
    per_member = {
        m: round(v, 4) for m, v in (
            (m, _member_ratio(many, m, 60.0, now)) for m in names)
        if v is not None}
    inv["ratio_identical_1_vs_n"] = (
        r1 is not None and rn is not None
        and abs(r1 - rn) <= 1e-9 * max(1.0, abs(r1)))
    return {"ratio_one_router": round(r1, 6) if r1 is not None else None,
            "ratio_n_routers": round(rn, 6) if rn is not None else None,
            # The lie a non-aggregating tier would tell: per-member
            # ratios scatter around the true mix.
            "per_member_ratios": per_member}


def _member_ratio(tier, member: str, window_s: float, now: float):
    lo = now - window_s
    sums = {"prefill": 0.0, "decode": 0.0}
    with tier._lock:
        for ts, name, kind, n in tier._ingress_log:
            if name == member and lo <= ts <= now:
                sums[kind] = sums.get(kind, 0.0) + n
    if sums["prefill"] <= 1e-9 or sums["decode"] <= 1e-9:
        return None
    return sums["prefill"] / sums["decode"]


# ---- partition-tolerance scenario (deterministic chaos plane) --------------


@dataclasses.dataclass
class PartitionConfig:
    """The partition-tolerance drill: every fault the chaos plane can
    script, thrown at the production seams, with recovery asserted — not
    hoped for. Four legs, one per degradation ladder:

    * **corruption** — a ``ChaosTransport`` flips payload bytes of a
      scheduled number of KV chunks while keeping the wire checksum
      truthful; the assembler's verify-at-commit must catch every one
      (``no_silent_corruption``) and the PR-10 bundle fallback must
      replay the wounded streams token-exact (``zero_dropped_streams``
      + ``bit_identical``).
    * **directory** — the directory wire partitions; the client's
      breaker opens, lookups degrade to the local-affinity answer
      FAST (``degraded_not_down``), and after heal exactly one
      half-open probe reconnects within the backoff bound
      (``recovery_bounded``).
    * **peer staleness** — a tier member goes silent on the peer feed;
      past the TTL its ring ranges spill to successors; one event after
      heal re-admits it.
    * **lease** — the leader's lease-store renewals start RAISING while
      its data writes still land; it must self-demote BEFORE the TTL so
      the standby's takeover never overlaps.
    """

    requests: int = 4
    prompt_len: int = 48
    max_new_tokens: int = 8
    corrupt_chunks: int = 2         # scheduled byzantine chunk budget
    model: str = "tiny"
    stale_ttl_s: float = 2.0        # peer-feed staleness TTL (drill clock)
    lease_ttl_s: float = 1.0
    recovery_bound_s: float = 5.0   # post-heal reconnect must beat this
    timeout_s: float = 120.0
    seed: int = 23


def run_partition(cfg: PartitionConfig) -> dict:
    from rbg_tpu.chaos import KINDS

    report: Dict[str, object] = {"scenario": "partition",
                                 "config": dataclasses.asdict(cfg)}
    inv: Dict[str, bool] = {}
    t_run = time.perf_counter()
    faults_before = {k: REGISTRY.counter(
        metric_names.CHAOS_FAULTS_INJECTED_TOTAL, kind=k) for k in KINDS}
    report["corruption"] = _partition_corruption_leg(cfg, inv)
    report["directory"] = _partition_directory_leg(cfg, inv)
    report["peer_staleness"] = _partition_staleness_leg(cfg, inv)
    report["lease"] = _partition_lease_leg(cfg, inv)
    # Every fault class the drill injected must have ACCOUNTED for
    # itself: a fault that doesn't count is a fault production can't see.
    injected = {k: round(REGISTRY.counter(
        metric_names.CHAOS_FAULTS_INJECTED_TOTAL, kind=k)
        - faults_before[k], 1) for k in KINDS}
    report["faults_injected"] = injected
    inv["all_faults_counted"] = all(v >= 1.0 for v in injected.values())
    report["elapsed_s"] = round(time.perf_counter() - t_run, 3)
    report["invariants"] = inv
    return report


def _partition_corruption_leg(cfg: PartitionConfig,
                              inv: Dict[str, bool]) -> dict:
    import numpy as np

    from rbg_tpu.chaos import (BROWNOUT, CORRUPT, ChaosClock,
                               ChaosTransport, FaultSchedule, FaultWindow)
    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.engine import Engine
    from rbg_tpu.engine.pd import PDStreamPair
    from rbg_tpu.kvtransfer import FakeICITransport

    page_size = 8
    ecfg = dict(model=cfg.model, page_size=page_size, num_pages=256,
                max_batch=4, max_seq_len=256, prefill_chunk=16,
                use_pallas="never")
    rng = np.random.RandomState(cfg.seed)
    eng_ref = Engine(EngineConfig(enable_radix_cache=False, **ecfg))
    vocab = eng_ref.mcfg.vocab_size
    prompts = [rng.randint(1, vocab, size=cfg.prompt_len).tolist()
               for _ in range(cfg.requests)]
    sp = SamplingParams(max_new_tokens=cfg.max_new_tokens)
    expect = eng_ref.generate(prompts, sp)

    # Scripted clock starts BEFORE the corrupt window so the jit-warming
    # passes ride a clean link; opening the window is one clock set, so
    # exactly the first ``corrupt_chunks`` drill chunks get wounded —
    # deterministic, replayable, seed-pinned.
    clock = ChaosClock(t0=-1.0)
    sched = FaultSchedule(
        [FaultWindow(CORRUPT, 0.0, float("inf"),
                     params={"max_faults": cfg.corrupt_chunks}),
         # Brownout rides the first drill window only (the clock jumps
         # past it after request 0): the wounded stream is ALSO slow —
         # corruption detection and token-exact replay must work on a
         # browned-out link, not just a fast one.
         FaultWindow(BROWNOUT, 0.0, 5.0, params={"delay_s": 0.004})],
        clock=clock, seed=cfg.seed)
    detected_before = REGISTRY.counter(
        metric_names.KVT_INTEGRITY_FAILURES_TOTAL, surface="chunk")
    link = ChaosTransport(FakeICITransport(bytes_per_s=1e9,
                                           latency_s=1e-4), sched)
    pair = PDStreamPair(EngineConfig(**ecfg), params=eng_ref.params,
                        transport=link)
    warm = rng.randint(1, vocab, size=cfg.prompt_len).tolist()
    for _ in range(2):
        pair.generate_one(warm, sp, stream=True, recv_timeout=60.0,
                          max_retries=2)
    clock.set(0.0)

    results: list = []
    failures: list = []
    for i, p in enumerate(prompts):
        try:
            results.append(pair.generate_one(p, sp, stream=True,
                                             recv_timeout=60.0,
                                             max_retries=3))
        except Exception as e:  # noqa: BLE001 — account, don't crash
            failures.append(f"request {i}: {type(e).__name__}: {e}")
            results.append(None)
        if i == 0:
            clock.set(10.0)   # brownout window closes; CORRUPT stays
                              # open but its budget is already spent

    bit_identical = all(r is not None and r["tokens"] == e
                        for r, e in zip(results, expect))
    detected = REGISTRY.counter(
        metric_names.KVT_INTEGRITY_FAILURES_TOTAL,
        surface="chunk") - detected_before
    retried = sum(r["retries"] for r in results if r)
    # The chain the ladder promises: every wounded chunk DETECTED at
    # commit (checksum, not luck), every wounded stream REPLAYED
    # (retries), every output BIT-IDENTICAL to the unified reference.
    inv["no_silent_corruption"] = (detected >= 1.0 and retried >= 1
                                   and bit_identical)
    inv["zero_dropped_streams"] = not failures and bit_identical
    return {
        "requests": cfg.requests,
        "completed": sum(1 for r in results if r),
        "corrupted_chunks_injected": cfg.corrupt_chunks,
        "integrity_failures_detected": round(detected, 1),
        "stream_retries": retried,
        "bit_identical": bit_identical,
        "failures": failures,
    }


def _partition_directory_leg(cfg: PartitionConfig,
                             inv: Dict[str, bool]) -> dict:
    import threading

    from rbg_tpu.chaos import (PARTITION, ChaosClock, FaultSchedule,
                               FaultWindow, directory_fault)
    from rbg_tpu.engine.kvpool import KVPoolServer, KVPoolStore
    from rbg_tpu.kvtransfer import PrefixDirectory
    from rbg_tpu.kvtransfer.directory import DirectoryClient

    d = PrefixDirectory(page_size=8)
    store = KVPoolStore(8, directory=d)
    srv = KVPoolServer(("127.0.0.1", 0), store)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    out: Dict[str, object] = {}
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        clock = ChaosClock(t0=0.0)
        sched = FaultSchedule(
            [FaultWindow(PARTITION, 1.0, 2.0,
                         params={"dead": ["router->directory"]})],
            clock=clock, seed=cfg.seed)
        c = DirectoryClient(addr, timeout=2.0, page_size=8, token="",
                            backoff_s=0.1, backoff_max_s=1.0,
                            chaos=directory_fault(sched))
        toks = list(range(24))
        assert c.register(toks, "10.0.0.5:9000", slice_id="sl-a") == 3
        assert c.lookup(toks) == (24, ["10.0.0.5:9000"])

        # ---- partition opens: degraded, not down ----
        clock.set(1.0)
        lat = []
        for _ in range(8):
            t0 = time.perf_counter()
            got = c.lookup(toks)
            lat.append(time.perf_counter() - t0)
            assert got == (0, []), "partitioned lookup must DEGRADE"
        out["degraded_lookup_ms"] = _pcts(lat)
        degraded_gauge = REGISTRY.gauge(metric_names.DEGRADED_MODE,
                                        ladder="directory")
        # Goodput floor: the degraded answer arrives ~instantly (breaker
        # short-circuit), never eats the 2 s wire timeout per request.
        inv["degraded_not_down"] = (max(lat) < 0.5
                                    and degraded_gauge == 1.0)

        # ---- heal: bounded recovery through the half-open probe ----
        clock.set(2.0)
        t0 = time.perf_counter()
        _wait(lambda: c.lookup(toks) == (24, ["10.0.0.5:9000"]),
              cfg.recovery_bound_s, "directory reconnect after heal")
        recovery_s = time.perf_counter() - t0
        out["recovery_s"] = round(recovery_s, 3)
        out["breaker_opens"] = round(REGISTRY.counter(
            metric_names.KVT_DIR_BREAKER_OPEN_TOTAL), 1)
        inv["recovery_bounded_directory"] = (
            recovery_s <= cfg.recovery_bound_s
            and REGISTRY.gauge(metric_names.DEGRADED_MODE,
                               ladder="directory") == 0.0)
    except (AssertionError, TimeoutError) as e:
        out["error"] = f"{type(e).__name__}: {e}"
        inv.setdefault("degraded_not_down", False)
        inv.setdefault("recovery_bounded_directory", False)
    finally:
        srv.shutdown()
        srv.server_close()
    return out


def _partition_staleness_leg(cfg: PartitionConfig,
                             inv: Dict[str, bool]) -> dict:
    from rbg_tpu.engine.routertier import EV_HEALTH, RouterTier

    clock = {"t": 100.0}
    tier = RouterTier(name="part", clock=lambda: clock["t"],
                      peer_stale_after_s=cfg.stale_ttl_s)
    for n in ("ra", "rb", "rc"):
        tier.register(n)
    keys = [f"sess-{i}" for i in range(64)]
    served0 = {tier.route(k) for k in keys}

    # rb partitions off the peer feed: ra/rc keep speaking, rb goes
    # silent past the TTL. Its ranges must spill to ring successors —
    # routing DEGRADES (fewer targets) instead of steering blind.
    clock["t"] += cfg.stale_ttl_s + 0.5
    for n in ("ra", "rc"):
        tier.publish(n, EV_HEALTH, {"ok": True})
    served_stale = {tier.route(k) for k in keys}
    stale_excluded = "rb" not in served_stale and served_stale <= {"ra",
                                                                   "rc"}
    gauge_stale = REGISTRY.gauge(metric_names.DEGRADED_MODE,
                                 ladder="peer_feed")

    # Heal: one event from rb is proof of life — re-admitted at once.
    tier.publish("rb", EV_HEALTH, {"ok": True})
    served_healed = {tier.route(k) for k in keys}
    gauge_healed = REGISTRY.gauge(metric_names.DEGRADED_MODE,
                                  ladder="peer_feed")

    inv["stale_peer_excluded"] = (stale_excluded and gauge_stale == 1.0)
    inv["recovery_bounded_peer_feed"] = ("rb" in served_healed
                                         and gauge_healed == 0.0)
    snap = tier.snapshot()
    return {
        "served_before": sorted(served0),
        "served_while_stale": sorted(served_stale),
        "served_after_heal": sorted(served_healed),
        "stale_ttl_s": cfg.stale_ttl_s,
        "members": snap.get("members"),
    }


def _partition_lease_leg(cfg: PartitionConfig,
                         inv: Dict[str, bool]) -> dict:
    from rbg_tpu.chaos import (SKEW, ChaosClock, FaultSchedule,
                               FaultWindow, SkewedClock)
    from rbg_tpu.runtime.ha import LeaderElector
    from rbg_tpu.runtime.store import Store

    store = Store()
    fail = {"on": False}

    class _FlakyLeaseStore:
        """The tentpole's exact failure: the COORDINATOR is unreachable
        (renewals raise) while the data-store write surface still
        works."""

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def renew_lease(self, *a, **kw):
            if fail["on"]:
                raise OSError("chaos: lease store unreachable")
            return self._inner.renew_lease(*a, **kw)

    clock = ChaosClock(t0=0.0)
    # The partitioned leader's clock ALSO skews forward mid-outage
    # (partitions and clock trouble travel together): the elector must
    # judge "how long since my last confirmed renewal" on its OWN skewed
    # view and still demote before the store-side TTL.
    sk = FaultSchedule(
        [FaultWindow(SKEW, 0.4, 1.0,
                     params={"offsets": {"plane-p": 0.2}})],
        clock=clock, seed=cfg.seed)
    skc = SkewedClock(clock, sk, "plane-p")

    class _Plane:
        def start(self):
            pass

        def stop(self):
            pass

    el = LeaderElector("plane-p", _FlakyLeaseStore(store),
                       lambda fenced: _Plane(), ttl_s=cfg.lease_ttl_s,
                       renew_period_s=cfg.lease_ttl_s / 5.0, clock=skc,
                       tail=False, self_demote_frac=0.5)

    def tick_at(t):
        clock.set(t)
        el.tick(now=skc())

    tick_at(0.0)
    assert el.is_leader
    tick_at(0.2)                         # healthy renewal at t=0.2
    # Coordinator partitions — but the DATA store is fine: the leader's
    # fenced writes keep landing. That is exactly why waiting out the
    # TTL is unsafe and self-demotion must come first.
    fail["on"] = True
    writes_land = False
    try:
        el.fenced_store.create(make_group("chaos-lease-w",
                                          simple_role("w", replicas=0)))
        writes_land = store.get("RoleBasedGroup", "default",
                                "chaos-lease-w") is not None
    except Exception:
        writes_land = False
    tick_at(0.3)                         # 0.1 s since last OK: holds on
    still_leading_early = el.is_leader
    tick_at(0.8)                         # skewed now=1.0: 0.8 s >= ttl/2
    demoted_at = 0.8                     # base-clock demotion moment
    lease_expiry = 0.2 + cfg.lease_ttl_s
    inv["leader_self_demoted_before_ttl"] = (
        writes_land and still_leading_early and not el.is_leader
        and el.self_demotions == 1 and demoted_at < lease_expiry)

    # Heal: re-campaign succeeds once the old epoch expires — recovery
    # is bounded by TTL + one renew period, on the DRILL clock.
    fail["on"] = False
    tick_at(lease_expiry + 0.01)
    inv["recovery_bounded_lease"] = el.is_leader and el.transitions == 2
    out = el.snapshot()
    out["writes_landed_during_partition"] = bool(writes_land)
    out["demoted_at_s"] = demoted_at
    out["lease_expiry_s"] = lease_expiry
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="rbg-tpu-stress")
    ap.add_argument("--scenario", default="churn",
                    choices=["churn", "overload", "preemption", "autoscale",
                             "kvstream", "prefixcache", "fleet", "topoflip",
                             "ha", "partition"],
                    help="churn = control-plane create/update/delete "
                         "percentiles; overload = serving-plane admission "
                         "control drill (sheds, deadlines, queue bound); "
                         "preemption = slice disruption drill (gang "
                         "semantics, deadline migration, router replay); "
                         "autoscale = capacity-follows-load drill (diurnal "
                         "+ burst trace against a live mini-plane, the "
                         "autoscaler closing the signal→capacity loop); "
                         "kvstream = KV transfer-plane drill (chunked "
                         "PD streaming over a slow/lossy link: overlap, "
                         "directory consistency, zero dropped streams); "
                         "fleet = 10k-node control-plane scale drill "
                         "(group churn at fleet scale: reconcile-latency "
                         "and scheduler-throughput curves, workqueue-"
                         "drains, stuck keys, event accounting); "
                         "topoflip = adaptive agg<->disagg drill (load-"
                         "mix-shifting trace, runtime PD-shape flips "
                         "with zero dropped streams, goodput vs both "
                         "static shapes); "
                         "partition = partition-tolerance drill "
                         "(deterministic chaos plane: byzantine chunk "
                         "corruption caught at commit + token-exact "
                         "replay, directory breaker degrade/recover, "
                         "peer-feed staleness spill, lease self-"
                         "demotion before TTL)")
    ap.add_argument("--clients", type=int, default=6)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--max-queue", type=int, default=4)
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--timeout-s", type=float, default=60.0)
    ap.add_argument("--slo-ttft-s", type=float, default=10.0,
                    help="TTFT target the overload drill's SLO judgment "
                         "uses (0 disables the dimension)")
    ap.add_argument("--slo-tpot-s", type=float, default=1.0,
                    help="per-output-token target for the overload "
                         "drill's SLO judgment (0 disables)")
    ap.add_argument("--warm-spares", type=int, default=1,
                    help="standby slices reserved per topology "
                         "(preemption scenario)")
    ap.add_argument("--kv-slow-link", type=float, default=None,
                    metavar="DELAY_S",
                    help="per-frame delay of the injected slow KV link "
                         "(kvstream scenario, default 0.02; adding it to "
                         "--scenario overload runs the kvstream drill "
                         "alongside and merges its invariants)")
    ap.add_argument("--kv-admit-layers", type=int, default=1,
                    metavar="K",
                    help="layer-sliced decode admission depth for the "
                         "kvstream drill: admit at layer-K coverage and "
                         "run the first decode step as a layer-windowed "
                         "chain under the transfer tail (0 = whole-"
                         "coverage admission)")
    ap.add_argument("--duration-s", type=float, default=None,
                    help="trace length for the autoscale (default 14) and "
                         "topoflip (default 15) scenarios")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved adaptive-vs-static repetitions for "
                         "the topoflip scenario (>=2 arms the goodput "
                         "gate; 1 = smoke, comparison reported ungated)")
    ap.add_argument("--no-token-exact", action="store_true",
                    help="skip the topoflip real-engine bit-identical "
                         "leg (mid-flip stream cut -> bundle fallback)")
    ap.add_argument("--burst-rps", type=float, default=85.0,
                    help="burst magnitude on top of the diurnal profile "
                         "(autoscale scenario)")
    ap.add_argument("--notice-s", type=float, default=25.0,
                    help="maintenance notice window before the deadline "
                         "(preemption scenario)")
    ap.add_argument("--nodes", type=int, default=None,
                    help="simulated fleet size for --scenario fleet "
                         "(default 5000; the acceptance drill runs >=5k)")
    ap.add_argument("--ab-reps", type=int, default=3,
                    help="event-plane throughput repetitions the fleet "
                         "drill runs after the main wave (0 disables; the "
                         "gate requires every rep to complete, dedup "
                         "engaged, and binds/s spread inside the trimmed "
                         "gate)")
    ap.add_argument("--ab-groups", type=int, default=40,
                    help="churn size per throughput repetition (fleet "
                         "scenario)")
    ap.add_argument("--reconcile-p99-bound-s", type=float, default=2.5,
                    help="reconcile p99 bound the fleet drill asserts "
                         "per controller")
    ap.add_argument("--groups", type=int, default=None,
                    help="groups to create (default: 10 for churn, "
                         "2 for preemption, 150 for fleet)")
    ap.add_argument("--roles", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--qps", type=float, default=5.0)
    ap.add_argument("--slices", type=int, default=None,
                    help="fake TPU slices (default: 64 for churn, "
                         "6 for preemption)")
    ap.add_argument("--hosts", type=int, default=None,
                    help="hosts per slice (default: 4 for churn, "
                         "2 for preemption)")
    ap.add_argument("--json", action="store_true", help="machine output only")
    ap.add_argument("--html", metavar="FILE", help="also write an HTML report")
    ap.add_argument("--backend", default="fake", choices=["fake", "k8s"],
                    help="fake = in-process FakeKubelet (kwok analog); "
                         "k8s = full mirror backend against the in-repo "
                         "fake apiserver over real HTTP")
    ap.add_argument("--json-out", metavar="FILE",
                    help="also write the JSON report to FILE (committed "
                         "per round like BENCH)")
    ap.add_argument("--locktrace", action="store_true",
                    help="run the scenario with the runtime lock-order "
                         "detector armed (RBG_LOCKTRACE=1): every shared "
                         "control-plane lock records its acquisition-order "
                         "graph and an inversion fails the run")
    ap.add_argument("--racetrace", action="store_true",
                    help="run the scenario with the guarded-field race "
                         "detector armed (RBG_RACETRACE=warn unless the "
                         "env var is already set): every write (and a "
                         "sampled read) of a `# guarded_by[...]` field "
                         "checks the owning lock is held; violations fail "
                         "the run via the race_free invariant")
    ap.add_argument("--jitwatch", action="store_true",
                    help="run the scenario with the compile/host-sync "
                         "sentry armed (RBG_JITWATCH=warn unless the env "
                         "var is already set): every XLA compile is "
                         "recorded; a cataloged program compiling AFTER "
                         "warmup_complete() fails the run via the "
                         "zero_unwarmed_compiles invariant")
    ap.add_argument("--wirecheck", action="store_true",
                    help="run the scenario with the wire-contract sentry "
                         "armed (RBG_WIRECHECK=warn unless the env var is "
                         "already set): every frame crossing the codec "
                         "seam is validated against api/ops.py (unknown "
                         "op, missing required field, undeclared "
                         "reply/error field); violations fail the run via "
                         "the wire_contract_clean invariant")
    ap.add_argument("--trace", action="store_true",
                    help="run the scenario with request tracing armed "
                         "(obs/trace.py): per-request hop spans, the "
                         "slowest-request waterfall in the report, and a "
                         "trace_complete invariant (every sampled request "
                         "forms one rooted span tree — no orphans/leaks)")
    ap.add_argument("--trace-sample", type=float, default=None,
                    metavar="RATE",
                    help="head-sampling rate for --trace (default 1.0 in "
                         "the drill so the report is deterministic; "
                         "production via RBG_TRACE_SAMPLE defaults to "
                         "0.01 + the sink always keeps the slowest-N)")
    args = ap.parse_args(argv)
    if args.trace_sample is not None:
        args.trace = True
    import os
    if args.locktrace:
        # Must be set BEFORE any plane/service objects are constructed —
        # named_lock reads the env var at lock-construction time.
        os.environ["RBG_LOCKTRACE"] = "1"
    if args.racetrace:
        # warn (record + count), not raise: the drill's job is to finish
        # and REPORT — the race_free invariant turns records into a red.
        # Same construction-time caveat as locktrace; arm() instruments
        # the registered classes before any instance exists.
        os.environ.setdefault("RBG_RACETRACE", "warn")
        from rbg_tpu.utils import racetrace
        racetrace.reset()
        racetrace.arm()
    if args.jitwatch:
        # warn, not raise — same rationale as racetrace: the drill's job
        # is to finish and REPORT; zero_unwarmed_compiles turns records
        # into a red. Armed BEFORE construction/warmup so the warmup
        # compile set is recorded (warmup_complete() arms the gate at
        # the end of _BatchService.warmup).
        os.environ.setdefault("RBG_JITWATCH", "warn")
        from rbg_tpu.utils import jitwatch
        jitwatch.disarm()
        jitwatch.arm()
    if args.wirecheck:
        # warn, not raise — the drill's job is to finish and REPORT;
        # wire_contract_clean turns records into a red. Armed BEFORE
        # scenario construction so every frame (scripted backends
        # included) crosses the patched codec seam.
        os.environ.setdefault("RBG_WIRECHECK", "warn")
        from rbg_tpu.utils import wirecheck
        wirecheck.disarm()
        wirecheck.arm()
    if args.trace:
        # Programmatic arming (env-var route: RBG_TRACE=1). Sample 1.0 by
        # default so a drill of a few dozen requests reliably fills the
        # waterfall; the sink is reset so the report reflects THIS run.
        from rbg_tpu.obs import trace as _trace
        _trace.configure(enabled=True,
                         sample=(1.0 if args.trace_sample is None
                                 else args.trace_sample))
        _trace.SINK.reset()
        # Counter baseline so _attach_trace judges only THIS run's
        # finalizations (in-process callers, e.g. tests, may have traced
        # before).
        args._trace_counter_base = {
            r: REGISTRY.counter(metric_names.TRACE_TRACES_TOTAL, result=r)
            for r in ("complete", "incomplete", "leaked")}
    load1 = os.getloadavg()[0]
    if args.scenario in ("overload", "preemption", "autoscale", "kvstream",
                         "prefixcache", "fleet", "topoflip", "ha",
                         "partition"):
        if args.scenario == "fleet":
            # Scenario-aware rate default: the churn scenarios' 5 qps
            # would spend 30 s just CREATING a 150-group fleet wave.
            qps = args.qps if args.qps != ap.get_default("qps") else 100.0
            report = run_fleet(FleetConfig(
                nodes=args.nodes or 5000,
                groups=args.groups or 150,
                roles_per_group=args.roles, replicas=args.replicas,
                create_qps=qps, hosts_per_slice=args.hosts or 4,
                reconcile_p99_bound_s=args.reconcile_p99_bound_s,
                ab_reps=max(0, args.ab_reps),
                ab_groups=max(1, args.ab_groups),
                timeout_s=max(args.timeout_s, 120.0)))
        elif args.scenario == "overload":
            report = run_serving_overload(OverloadConfig(
                clients=args.clients, requests_per_client=args.requests,
                max_queue=args.max_queue, max_batch=args.max_batch,
                timeout_s=args.timeout_s,
                slo_ttft_s=args.slo_ttft_s, slo_tpot_s=args.slo_tpot_s))
            if args.kv_slow_link is not None:
                # Transfer-plane drill riding along: slow-link streaming
                # PD invariants merge into the overload report (one red
                # anywhere fails the run).
                kv = run_kv_stream(KVStreamConfig(
                    slow_link_delay_s=args.kv_slow_link,
                    admit_layers=args.kv_admit_layers))
                report["kvstream"] = {k: v for k, v in kv.items()
                                      if k != "invariants"}
                report["invariants"].update(kv["invariants"])
        elif args.scenario == "kvstream":
            report = run_kv_stream(KVStreamConfig(
                slow_link_delay_s=(args.kv_slow_link
                                   if args.kv_slow_link is not None
                                   else 0.02),
                admit_layers=args.kv_admit_layers))
        elif args.scenario == "prefixcache":
            report = run_prefix_cache(PrefixCacheConfig(
                slo_ttft_s=min(args.slo_ttft_s, 0.6)))
        elif args.scenario == "autoscale":
            report = run_autoscale(AutoscaleStressConfig(
                duration_s=(args.duration_s if args.duration_s is not None
                            else 14.0),
                burst_rps=args.burst_rps,
                timeout_s=args.timeout_s))
        elif args.scenario == "topoflip":
            report = run_topoflip(TopoFlipConfig(
                duration_s=(args.duration_s if args.duration_s is not None
                            else 15.0),
                reps=max(1, args.reps),
                token_exact=not args.no_token_exact,
                timeout_s=args.timeout_s))
        elif args.scenario == "ha":
            report = run_ha(HAConfig(timeout_s=args.timeout_s))
        elif args.scenario == "partition":
            report = run_partition(PartitionConfig(
                timeout_s=args.timeout_s))
        else:
            report = run_preemption(PreemptionConfig(
                groups=max(2, args.groups) if args.groups else 2,
                slices=args.slices or 6, hosts_per_slice=args.hosts or 2,
                warm_spares=args.warm_spares,
                notice_deadline_s=args.notice_s,
                timeout_s=args.timeout_s))
        report["load1_before"] = round(load1, 2)
        _attach_locktrace(report, args)
        _attach_racetrace(report, args)
        _attach_jitwatch(report, args)
        _attach_wirecheck(report, args)
        _attach_trace(report, args)
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(report, f, indent=1)
        if args.html:
            write_html_report(report, args.html)
        print(json.dumps(report) if args.json
              else json.dumps(report, indent=2))
        # The drill ASSERTS its invariants: a red one is a failed run.
        if not all(report.get("invariants", {}).values()):
            return 1
        return 0
    cfg = StressConfig(groups=args.groups or 10, roles_per_group=args.roles,
                       replicas=args.replicas, create_qps=args.qps,
                       slices=args.slices or 64, hosts_per_slice=args.hosts or 4,
                       backend=args.backend)
    report = run_stress(cfg)
    report["load1_before"] = round(load1, 2)
    report["command"] = "rbg-tpu stress " + " ".join(
        argv if argv is not None else __import__("sys").argv[1:])
    _attach_locktrace(report, args)
    _attach_racetrace(report, args)
    _attach_jitwatch(report, args)
    _attach_wirecheck(report, args)
    _attach_trace(report, args)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=1)
    if args.html:
        write_html_report(report, args.html)
    if args.json:
        print(json.dumps(report))
    else:
        print(json.dumps(report, indent=2))
    if report.get("locktrace", {}).get("inversions"):
        return 1
    if report.get("racetrace", {}).get("violations"):
        return 1
    if report.get("jitwatch", {}).get("violations"):
        return 1
    if report.get("wirecheck", {}).get("violations"):
        return 1
    return 0


def _attach_locktrace(report: dict, args) -> None:
    """Fold the lock-order graph into the report when --locktrace ran, and
    add an invariant so an inversion fails the drill like any other red."""
    if not getattr(args, "locktrace", False):
        return
    from rbg_tpu.utils import locktrace
    report["locktrace"] = {"order_graph": locktrace.snapshot(),
                           "inversions": locktrace.inversions()}
    if "invariants" in report:
        report["invariants"]["lock_order_acyclic"] = (
            not locktrace.inversions())


def _attach_jitwatch(report: dict, args) -> None:
    """Fold the compile-sentry verdict into the report when --jitwatch
    ran: the counters, every post-warmup compile of a cataloged program
    (with shape signature + origin stack), and the
    zero_unwarmed_compiles invariant so one fails the drill red."""
    if not getattr(args, "jitwatch", False):
        return
    from rbg_tpu.utils import jitwatch
    report["jitwatch"] = {
        "counters": jitwatch.counters(),
        "warmed_programs": sorted(jitwatch.warmed_programs()),
        "unwarmed_by_program": jitwatch.unwarmed_by_program(),
        "violations": jitwatch.violations(),
    }
    if "invariants" in report:
        report["invariants"]["zero_unwarmed_compiles"] = (
            not jitwatch.violations())
    jitwatch.disarm()


def _attach_wirecheck(report: dict, args) -> None:
    """Fold the wire-contract sentry verdict into the report when
    --wirecheck ran: frames checked, per-(op, kind) violation counts, the
    first violation descriptions, and the wire_contract_clean invariant
    so one fails the drill red."""
    if not getattr(args, "wirecheck", False):
        return
    from rbg_tpu.utils import wirecheck
    report["wirecheck"] = {
        "counters": wirecheck.counters(),
        "violations_by_key": wirecheck.violations_by_key(),
        "violations": wirecheck.violations()[:20],
    }
    if "invariants" in report:
        report["invariants"]["wire_contract_clean"] = (
            not wirecheck.violations())
    wirecheck.disarm()


def _attach_trace(report: dict, args) -> None:
    """Fold the trace sink into the report when --trace ran: the
    slowest-request waterfall, per-trace summaries, and two invariants —
    ``trace_complete`` (every sampled request's spans form one rooted
    tree: no orphans, no leaked/never-ended roots) and, for the overload
    drill, ``trace_hops_cover_root`` (the hop durations of the slowest
    request sum — union of intervals, so retries don't double-count — to
    ≥90% of its root span: the waterfall explains the latency it reports).
    """
    if not getattr(args, "trace", False):
        return
    from rbg_tpu.obs import trace
    recent = trace.SINK.recent(64)
    slowest = trace.SINK.slowest(10)
    active = trace.SINK.active_count()
    cov = trace.hop_coverage(slowest[0]) if slowest else None
    # Soundness comes from the per-finalization counters, not the recent
    # ring (capped at 64 — a drill can finalize far more, and an orphan
    # evicted from the ring must still red the invariant). The ring only
    # supplies concrete example trace_ids for the report.
    base = getattr(args, "_trace_counter_base", {})
    totals = {r: max(0.0, REGISTRY.counter(metric_names.TRACE_TRACES_TOTAL,
                                           result=r) - base.get(r, 0.0))
              for r in ("complete", "incomplete", "leaked")}
    seen = {}
    for r in recent + slowest:
        seen[r["trace_id"]] = r
    incomplete = [tid for tid, r in seen.items() if not r["complete"]]
    report["trace"] = {
        "sampled_finalized": int(sum(totals.values())),
        "finalized_by_result": {k: int(v) for k, v in totals.items()},
        "active_unfinalized": active,
        "incomplete": incomplete,
        "slowest": slowest[:5],
        "waterfall": trace.waterfall(slowest[0]) if slowest else [],
        "hop_coverage": round(cov, 4) if cov is not None else None,
    }
    if "invariants" in report:
        report["invariants"]["trace_complete"] = (
            totals["complete"] > 0 and totals["incomplete"] == 0
            and totals["leaked"] == 0 and active == 0)
        if getattr(args, "scenario", "") == "overload":
            report["invariants"]["trace_hops_cover_root"] = (
                cov is not None and cov >= 0.9)


def _attach_racetrace(report: dict, args) -> None:
    """Fold the guarded-access verdict into the report when --racetrace
    ran: the rbg_race_* counters, the recorded violations, and a
    ``race_free`` invariant that reds the drill on any of them."""
    if not getattr(args, "racetrace", False):
        return
    from rbg_tpu.utils import racetrace
    report["racetrace"] = {"counters": racetrace.counters(),
                           "violations": racetrace.violations()}
    if "invariants" in report:
        report["invariants"]["race_free"] = not racetrace.violations()


def _kv_table(d: dict) -> str:
    return ("<table><tr><th>key</th><th>value</th></tr>"
            + "".join(f"<tr><td>{k}</td><td>{v}</td></tr>"
                      for k, v in d.items())
            + "</table>")


def _invariants_table(inv: dict) -> str:
    rows = "".join(
        f"<tr><td>{k}</td><td style=\"color:{'#070' if v else '#b00'}\">"
        f"{'PASS' if v else 'FAIL'}</td></tr>"
        for k, v in inv.items())
    return f"<table><tr><th>invariant</th><th>result</th></tr>{rows}</table>"


def _churn_sections(report: dict) -> str:
    rows = []
    for phase in ("create_to_ready_ms", "update_to_converged_ms",
                  "delete_to_gone_ms"):
        p = report.get(phase) or {}
        rows.append(
            f"<tr><td>{phase.replace('_', ' ')}</td>"
            f"<td>{p.get('p50', 0)}</td><td>{p.get('p90', 0)}</td>"
            f"<td>{p.get('p99', 0)}</td><td>{p.get('max', 0)}</td>"
            f"<td>{p.get('n', 0)}</td></tr>")
    rec = "".join(
        f"<tr><td>{c}</td><td>{v}</td></tr>"
        for c, v in (report.get("reconcile_p99_s") or {}).items())
    prof = report.get("create_phase_profile") or {}
    prof_rows = "".join(
        f"<tr><td>{t['site']}</td><td>{t['samples']}</td></tr>"
        for t in prof.get("top", [])[:15])
    return f"""<table><tr><th>phase</th><th>p50 (ms)</th><th>p90</th>
<th>p99</th><th>max</th><th>n</th></tr>{"".join(rows)}</table>
<h2>reconcile p99 (s)</h2>
<table><tr><th>controller</th><th>p99</th></tr>{rec}</table>
<h2>create-phase CPU profile (top sample sites,
{prof.get("samples", 0)} samples)</h2>
<table><tr><th>site</th><th>samples</th></tr>{prof_rows}</table>"""


def _slo_sections(report: dict) -> str:
    slo = report.get("slo") or {}
    if not slo:
        return ""
    gvt = report.get("goodput_vs_throughput") or {}
    roles = slo.get("per_role_60s") or slo.get("per_role") or {}
    rows = "".join(
        f"<tr><td>{gk}</td><td>{g.get('judged', 0)}</td>"
        f"<td>{g.get('ttft_attainment')}</td>"
        f"<td>{g.get('tpot_attainment')}</td>"
        f"<td>{g.get('goodput_rps')}</td></tr>"
        for gk, g in sorted(roles.items()))
    out = (f"<h2>SLO attainment (targets: {json.dumps(slo.get('targets'))}, "
           f"judged: {slo.get('judged')})</h2>"
           f"<table><tr><th>role</th><th>judged</th><th>ttft att</th>"
           f"<th>tpot att</th><th>goodput rps</th></tr>{rows}</table>")
    if gvt:
        out += f"<h2>goodput vs throughput</h2>{_kv_table(gvt)}"
    return out


def _overload_sections(report: dict) -> str:
    lat = report.get("admitted_latency_ms") or {}
    return f"""<h2>outcomes</h2>{_kv_table(report.get("outcomes") or {})}
<h2>admitted-request latency (ms)</h2>{_kv_table(lat)}
<h2>continuous batching</h2>{_kv_table(
        report.get("continuous_batching") or {})}
<h2>service counters</h2>{_kv_table(report.get("service") or {})}
<p>max queue depth observed: {report.get("max_queue_depth_observed")}
&nbsp; retry_after hint: {report.get("retry_after_hint_s")}</p>
{_slo_sections(report)}
<h2>invariants</h2>{_invariants_table(report.get("invariants") or {})}"""


def _autoscale_curve_html(report: dict) -> str:
    """Capacity-vs-load curve: two stacked single-axis panels over one
    time axis (req/s above, replicas below — different units never share
    an axis), thin 2px lines, recessive grid, legend + line-end labels,
    a crosshair hover layer, and a data-table view."""
    curve = report.get("curve") or []
    if len(curve) < 2:
        return "<p>(no curve samples)</p>"
    ml, mr, mt, ph, gap, iw = 46, 96, 14, 132, 30, 560
    W = ml + iw + mr
    x1 = curve[-1]["t"] or 1.0
    panels = [
        ("req/s", (("offered_rps", "offered", "#2a78d6"),
                   ("capacity_rps", "capacity", "#eb6834"))),
        ("replicas", (("target", "target", "#1baf7a"),
                      ("actual", "actual", "#eda100"))),
    ]
    svg = []
    H = mt + ph * 2 + gap + 22
    svg.append(f'<svg id="asc-svg" viewBox="0 0 {W} {H}" width="{W}" '
               f'height="{H}" role="img" '
               f'aria-label="capacity vs load over time">')
    for pi, (unit, series) in enumerate(panels):
        top = mt + pi * (ph + gap)
        ymax = max(max(c[k] for c in curve) for k, _, _ in series) or 1.0
        ymax = float(__import__("math").ceil(ymax * 1.1))
        for gi in range(5):
            gy = top + ph - gi * ph / 4
            val = ymax * gi / 4
            svg.append(
                f'<line x1="{ml}" y1="{gy:.1f}" x2="{ml + iw}" '
                f'y2="{gy:.1f}" stroke="#e4e3de" stroke-width="1"/>'
                f'<text x="{ml - 6}" y="{gy + 3.5:.1f}" text-anchor="end" '
                f'class="vt">{val:g}</text>')
        svg.append(f'<text x="{ml}" y="{top - 4}" class="vt">{unit}</text>')
        for key, label, color in series:
            pts = " ".join(
                f'{ml + c["t"] / x1 * iw:.1f},'
                f'{top + ph - min(1.0, c[key] / ymax) * ph:.1f}'
                for c in curve)
            last = curve[-1]
            ly = top + ph - min(1.0, last[key] / ymax) * ph
            svg.append(
                f'<polyline points="{pts}" fill="none" stroke="{color}" '
                f'stroke-width="2" stroke-linejoin="round"/>'
                f'<circle cx="{ml + iw:.1f}" cy="{ly:.1f}" r="4" '
                f'fill="{color}"/>'
                f'<text x="{ml + iw + 8}" y="{ly + 3.5:.1f}" class="vl">'
                f'{label} {last[key]:g}</text>')
    for tx in range(0, 5):
        t = x1 * tx / 4
        px = ml + t / x1 * iw
        svg.append(f'<text x="{px:.1f}" y="{H - 6}" text-anchor="middle" '
                   f'class="vt">{t:.1f}s</text>')
    # Burst window shading (context, behind the hover layer).
    cfg = report.get("config") or {}
    if cfg.get("burst_start_frac") is not None:
        bx0 = ml + cfg["burst_start_frac"] * iw
        bx1 = ml + cfg["burst_end_frac"] * iw
        svg.insert(1, f'<rect x="{bx0:.1f}" y="{mt}" '
                      f'width="{bx1 - bx0:.1f}" '
                      f'height="{ph * 2 + gap}" fill="#52514e" '
                      f'opacity="0.06"/>')
    svg.append(f'<line id="asc-cross" x1="0" x2="0" y1="{mt}" '
               f'y2="{mt + ph * 2 + gap}" stroke="#52514e" '
               f'stroke-width="1" opacity="0"/>')
    svg.append(f'<rect id="asc-hit" x="{ml}" y="{mt}" width="{iw}" '
               f'height="{ph * 2 + gap}" fill="transparent"/>')
    svg.append("</svg>")
    legend = "".join(
        f'<span class="chip" style="background:{color}"></span>'
        f'<span class="vl">{label}</span>'
        for _, series in panels for _, label, color in series)
    step = max(1, len(curve) // 40)
    rows = "".join(
        f'<tr><td>{c["t"]}</td><td>{c["offered_rps"]}</td>'
        f'<td>{c["capacity_rps"]}</td><td>{c["target"]}</td>'
        f'<td>{c["actual"]}</td><td>{c["queue"]}</td></tr>'
        for c in curve[::step])
    data = json.dumps([[c["t"], c["offered_rps"], c["capacity_rps"],
                        c["target"], c["actual"]] for c in curve])
    return f"""<div class="viz-root" style="position:relative">
<style>.viz-root{{color-scheme:light}}
.viz-root .vt{{font:10px sans-serif;fill:#52514e}}
.viz-root .vl{{font:11px sans-serif;fill:#0b0b0b;color:#0b0b0b;
margin-right:10px}}
.viz-root .chip{{display:inline-block;width:10px;height:10px;
border-radius:2px;margin:0 4px 0 0;vertical-align:-1px}}
#asc-tip{{position:absolute;display:none;background:#fff;
border:1px solid #c3c2b7;border-radius:4px;padding:4px 8px;
font:11px sans-serif;color:#0b0b0b;pointer-events:none;
box-shadow:0 1px 3px rgba(0,0,0,.15)}}</style>
<div>{legend}</div>
{"".join(svg)}
<div id="asc-tip"></div>
<script>(function(){{
var D={data}, svg=document.getElementById("asc-svg"),
 tip=document.getElementById("asc-tip"),
 cross=document.getElementById("asc-cross"),
 hit=document.getElementById("asc-hit"),
 ml={ml}, iw={iw}, x1={x1};
hit.addEventListener("mousemove", function(ev){{
 var pt=svg.createSVGPoint(); pt.x=ev.clientX; pt.y=ev.clientY;
 var p=pt.matrixTransform(svg.getScreenCTM().inverse());
 var t=(p.x-ml)/iw*x1, best=D[0], bd=1e9;
 for (var i=0;i<D.length;i++) {{var d=Math.abs(D[i][0]-t);
  if(d<bd){{bd=d;best=D[i];}}}}
 cross.setAttribute("x1", ml+best[0]/x1*iw);
 cross.setAttribute("x2", ml+best[0]/x1*iw);
 cross.setAttribute("opacity", "0.5");
 tip.style.display="block";
 tip.style.left=(ev.offsetX+14)+"px"; tip.style.top=(ev.offsetY+8)+"px";
 tip.innerHTML="t="+best[0].toFixed(2)+"s<br>offered "+best[1]
  +" r/s<br>capacity "+best[2]+" r/s<br>target "+best[3]
  +" · actual "+best[4];
}});
hit.addEventListener("mouseleave", function(){{
 tip.style.display="none"; cross.setAttribute("opacity","0");}});
}})();</script>
<details><summary>data table</summary>
<table><tr><th>t (s)</th><th>offered r/s</th><th>capacity r/s</th>
<th>target</th><th>actual</th><th>queue</th></tr>{rows}</table>
</details></div>"""


def _autoscale_sections(report: dict) -> str:
    req = report.get("requests") or {}
    reaction = {
        "burst_react_s": report.get("burst_react_s"),
        "burst_react_bound_s": report.get("burst_react_bound_s"),
        "peak_target": report.get("peak_target"),
        "end_target": report.get("end_target"),
    }
    roles = ((report.get("autoscale_status") or {}).get("roles")) or []
    role_rows = "".join(
        f"<tr><td>{r.get('role')}</td><td>{r.get('target')}</td>"
        f"<td>{r.get('actual')}</td>"
        f"<td>{'yes' if r.get('enabled') else 'no'}</td>"
        f"<td>{(r.get('last_decision') or {}).get('direction')}: "
        f"{(r.get('last_decision') or {}).get('reason')}</td></tr>"
        for r in roles)
    return f"""<h2>capacity vs load</h2>{_autoscale_curve_html(report)}
<h2>burst reaction</h2>{_kv_table(reaction)}
<h2>requests</h2>{_kv_table(req)}
<h2>autoscaler decisions (this run)</h2>{_kv_table(
        report.get("decisions") or {})}
<h2>autoscaler posture at end</h2>
<table><tr><th>role</th><th>target</th><th>actual</th><th>enabled</th>
<th>last decision</th></tr>{role_rows}</table>
<h2>invariants</h2>{_invariants_table(report.get("invariants") or {})}"""


def _preemption_sections(report: dict) -> str:
    phases = dict(report.get("phases") or {})
    replay = phases.pop("router_replay", {}) or {}
    return f"""<h2>recovery timings</h2>{_kv_table(phases)}
<h2>router replay / rolling drain</h2>{_kv_table(
        {k: v for k, v in replay.items()
         if k not in ("drain_reply", "slo")})}
<h2>rbg_disruption_* (this run)</h2>{_kv_table(
        report.get("disruption_counters") or {})}
<p>spare-pool depth at end: {report.get("spare_pool_depth")}</p>
{_slo_sections(report)}
<h2>invariants</h2>{_invariants_table(report.get("invariants") or {})}"""


def _topoflip_posture_html(report: dict) -> str:
    """Posture-vs-load-mix timeline: the measured prompt:output token
    ratio (with the two hysteresis thresholds) above the goodput curve,
    with the POSTURE BAND — unified / flipping / disagg — shaded behind
    both panels, so a flip is visually attributable to the mix shift
    that caused it (PR-9 SVG panel style: stacked single-axis panels,
    thin lines, recessive grid, line-end labels)."""
    curve = report.get("curve") or []
    if len(curve) < 2:
        return "<p>(no curve samples)</p>"
    cfg = report.get("config") or {}
    ml, mr, mt, ph, gap, iw = 46, 110, 16, 120, 30, 560
    W = ml + iw + mr
    H = mt + ph * 2 + gap + 22
    x1 = curve[-1]["t"] or 1.0

    def x(t):
        return ml + t / x1 * iw

    # Posture band segments (drawn first, behind everything).
    band_colors = {"unified": "#2a78d6", "disagg": "#eb6834"}
    segs = []
    seg_start, seg_key = curve[0]["t"], (curve[0]["posture"],
                                         bool(curve[0]["state"]))
    for c in curve[1:] + [None]:
        key = (c["posture"], bool(c["state"])) if c else None
        if key != seg_key:
            t_end = c["t"] if c else curve[-1]["t"]
            color = "#52514e" if seg_key[1] else \
                band_colors.get(seg_key[0], "#52514e")
            segs.append((seg_start, t_end, color, seg_key))
            if c:
                seg_start, seg_key = c["t"], key
    svg = [f'<svg viewBox="0 0 {W} {H}" width="{W}" height="{H}" '
           f'role="img" aria-label="posture vs load mix over time">']
    for t0s, t1s, color, key in segs:
        svg.append(f'<rect x="{x(t0s):.1f}" y="{mt}" '
                   f'width="{max(0.5, x(t1s) - x(t0s)):.1f}" '
                   f'height="{ph * 2 + gap}" fill="{color}" '
                   f'opacity="{0.16 if key[1] else 0.08}"/>')
    panels = [
        ("prompt:output ratio", "ratio",
         lambda: max(max((c["ratio"] or 0) for c in curve), 1.0) * 1.1,
         "#8a4fd3"),
        ("goodput fraction", "goodput_frac", lambda: 1.05, "#1baf7a"),
    ]
    for pi, (unit, kkey, ymax_fn, color) in enumerate(panels):
        top = mt + pi * (ph + gap)
        ymax = float(ymax_fn())
        for gi in range(5):
            gy = top + ph - gi * ph / 4
            val = ymax * gi / 4
            svg.append(
                f'<line x1="{ml}" y1="{gy:.1f}" x2="{ml + iw}" '
                f'y2="{gy:.1f}" stroke="#e4e3de" stroke-width="1"/>'
                f'<text x="{ml - 6}" y="{gy + 3.5:.1f}" text-anchor="end" '
                f'class="vt">{val:.2g}</text>')
        svg.append(f'<text x="{ml}" y="{top - 4}" class="vt">{unit}</text>')
        if kkey == "ratio":
            for thr, lbl in ((cfg.get("unified_ratio"), "unified<="),
                             (cfg.get("disagg_ratio"), "disagg>=")):
                if not thr or thr > ymax:
                    continue
                ty = top + ph - min(1.0, thr / ymax) * ph
                svg.append(
                    f'<line x1="{ml}" y1="{ty:.1f}" x2="{ml + iw}" '
                    f'y2="{ty:.1f}" stroke="#c23a6b" stroke-width="1" '
                    f'stroke-dasharray="4 3"/>'
                    f'<text x="{ml + iw + 8}" y="{ty + 3.5:.1f}" '
                    f'class="vt">{lbl}{thr:g}</text>')
        pts = " ".join(
            f'{x(c["t"]):.1f},'
            f'{top + ph - min(1.0, (c[kkey] or 0) / ymax) * ph:.1f}'
            for c in curve)
        last = curve[-1]
        ly = top + ph - min(1.0, (last[kkey] or 0) / ymax) * ph
        svg.append(
            f'<polyline points="{pts}" fill="none" stroke="{color}" '
            f'stroke-width="2" stroke-linejoin="round"/>'
            f'<circle cx="{ml + iw:.1f}" cy="{ly:.1f}" r="4" '
            f'fill="{color}"/>'
            f'<text x="{ml + iw + 8}" y="{ly + 3.5:.1f}" class="vl">'
            f'{(last[kkey] or 0):g}</text>')
    for tx in range(0, 5):
        t = x1 * tx / 4
        svg.append(f'<text x="{x(t):.1f}" y="{H - 6}" '
                   f'text-anchor="middle" class="vt">{t:.1f}s</text>')
    svg.append("</svg>")
    legend = "".join(
        f'<span class="chip" style="background:{c};opacity:.35"></span>'
        f'<span class="vl">{lbl}</span>'
        for lbl, c in (("unified posture", band_colors["unified"]),
                       ("disagg posture", band_colors["disagg"]),
                       ("flip in progress", "#52514e")))
    step = max(1, len(curve) // 40)
    rows = "".join(
        f'<tr><td>{c["t"]}</td><td>{c["long_frac"]}</td>'
        f'<td>{c["ratio"]}</td><td>{c["posture"]}'
        f'{("/" + c["state"]) if c["state"] else ""}</td>'
        f'<td>{c["queue"]}</td><td>{c["goodput_frac"]}</td></tr>'
        for c in curve[::step])
    return f"""<div class="viz-root">
<style>.viz-root{{color-scheme:light}}
.viz-root .vt{{font:10px sans-serif;fill:#52514e}}
.viz-root .vl{{font:11px sans-serif;fill:#0b0b0b;color:#0b0b0b;
margin-right:10px}}
.viz-root .chip{{display:inline-block;width:10px;height:10px;
border-radius:2px;margin:0 4px 0 0;vertical-align:-1px}}</style>
<div>{legend}</div>
{"".join(svg)}
<details><summary>data table</summary>
<table><tr><th>t (s)</th><th>long frac</th><th>ratio</th>
<th>posture</th><th>queue</th><th>goodput frac</th></tr>{rows}</table>
</details></div>"""


def _topoflip_sections(report: dict) -> str:
    med = report.get("median_goodput") or {}
    flip = {
        "converge_bound_s": report.get("converge_bound_s"),
        "spread (trimmed)":
            f"{report.get('spread')} (max {report.get('spread_max')})",
        "attempt": report.get("attempt"),
    }
    rep_rows = "".join(
        f"<tr><td>{m}</td><td>{r['goodput_fraction']}</td>"
        f"<td>{r['arrivals']}</td><td>{r['shed']}</td>"
        f"<td>{r['dropped_streams']}</td>"
        f"<td>{sum((r.get('flips') or {}).values()):g}</td>"
        f"<td>{r.get('flip_started_after_shift_s')}</td>"
        f"<td>{r.get('end_posture')}</td></tr>"
        for m, rs in (report.get("reps") or {}).items() for r in rs)
    te = report.get("token_exact")
    te_html = (f"<h2>token-exact leg (mid-flip stream cut → bundle "
               f"fallback)</h2>{_kv_table(te)}" if te else "")
    return f"""<h2>posture vs load mix</h2>{_topoflip_posture_html(report)}
<h2>goodput: adaptive vs both static shapes (median of interleaved
reps)</h2>{_kv_table(med)}
<h2>per-rep results</h2>
<table><tr><th>variant</th><th>goodput frac</th><th>arrivals</th>
<th>shed</th><th>dropped</th><th>flips</th><th>flip react (s)</th>
<th>end posture</th></tr>{rep_rows}</table>
<h2>flip discipline</h2>{_kv_table(flip)}
{te_html}
<h2>invariants</h2>{_invariants_table(report.get("invariants") or {})}"""


def _kvstream_sections(report: dict) -> str:
    tr = report.get("transfer") or {}
    return f"""<h2>requests</h2>{_kv_table(report.get("requests") or {})}
<h2>transfer (slow link)</h2>{_kv_table(
        {k: v for k, v in tr.items()
         if not isinstance(v, dict)})}
<h2>admit lead ms (ready → stream close)</h2>{_kv_table(
        tr.get("admit_lead_ms") or {})}
<h2>layer-sliced admission (coverage at admit)</h2>{_kv_table(
        {k: v for k, v in (tr.get("layer_admit") or {}).items()
         if not isinstance(v, list)})}
<p>per-stream [layers_at_admit, total_layers] (null = plain path):
{(tr.get("layer_admit") or {}).get("coverage_at_admit")}</p>
<h2>prefix pool</h2>{_kv_table(report.get("pool") or {})}
<h2>prefix directory</h2>{_kv_table(report.get("directory") or {})}
<p>bit_identical: {report.get("bit_identical")}</p>
<h2>invariants</h2>{_invariants_table(report.get("invariants") or {})}"""


_FLEET_COLORS = ("#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#8a4fd3",
                 "#c23a6b", "#52514e", "#0b8a9e")


def _fleet_latency_svg(latency: Dict[str, dict]) -> str:
    """Per-controller reconcile-latency percentile curves: x = percentile
    position, y = latency on a log scale (p50 and p99 of a control plane
    differ by orders of magnitude — linear axes flatten every curve but
    the worst one)."""
    import math
    if not latency:
        return "<p>(no reconcile samples)</p>"
    ml, mr, mt, ph, iw = 52, 150, 14, 160, 420
    W, H = ml + iw + mr, mt + ph + 26
    pcts = [p["pct"] for p in next(iter(latency.values()))["curve"]]
    xs = {p: ml + i * iw / (len(pcts) - 1) for i, p in enumerate(pcts)}
    all_ms = [max(0.001, p["ms"]) for v in latency.values()
              for p in v["curve"]]
    lo = math.floor(math.log10(min(all_ms)))
    hi = math.ceil(math.log10(max(all_ms)))
    if hi <= lo:  # ceil can legitimately be 0 — don't truthiness-test it
        hi = lo + 1

    def y(ms):
        f = (math.log10(max(0.001, ms)) - lo) / (hi - lo)
        return mt + ph - min(1.0, max(0.0, f)) * ph

    svg = [f'<svg viewBox="0 0 {W} {H}" width="{W}" height="{H}" '
           f'role="img" aria-label="reconcile latency percentiles">']
    for d in range(lo, hi + 1):
        gy = y(10 ** d)
        svg.append(f'<line x1="{ml}" y1="{gy:.1f}" x2="{ml + iw}" '
                   f'y2="{gy:.1f}" stroke="#e4e3de"/>'
                   f'<text x="{ml - 6}" y="{gy + 3.5:.1f}" '
                   f'text-anchor="end" class="vt">{10 ** d:g}ms</text>')
    for p in pcts:
        svg.append(f'<text x="{xs[p]:.1f}" y="{H - 8}" '
                   f'text-anchor="middle" class="vt">p{p}</text>')
    for i, (c, v) in enumerate(sorted(latency.items())):
        color = _FLEET_COLORS[i % len(_FLEET_COLORS)]
        pts = " ".join(f'{xs[p["pct"]]:.1f},{y(p["ms"]):.1f}'
                       for p in v["curve"])
        last = v["curve"][-1]
        svg.append(f'<polyline points="{pts}" fill="none" stroke="{color}" '
                   f'stroke-width="2" stroke-linejoin="round"/>'
                   f'<text x="{ml + iw + 6}" '
                   f'y="{y(last["ms"]) + 3.5:.1f}" class="vl" '
                   f'fill="{color}">{c} {last["ms"]:g}ms</text>')
    svg.append("</svg>")
    return "".join(svg)


def _fleet_throughput_svg(curve: List[dict]) -> str:
    """Scheduler-throughput curve over the drill: binds/s + reconciles/s
    (one rate panel) and summed workqueue depth (its own panel — depth is
    not a rate)."""
    if len(curve) < 2:
        return "<p>(no throughput samples)</p>"
    ml, mr, mt, ph, gap, iw = 52, 130, 14, 110, 28, 460
    W = ml + iw + mr
    H = mt + ph * 2 + gap + 24
    x1 = curve[-1]["t"] or 1.0
    panels = [
        ("/s", (("binds_per_s", "sched binds", "#2a78d6"),
                ("reconciles_per_s", "reconciles", "#eb6834"),
                ("events_per_s", "events", "#1baf7a"))),
        ("queue depth", (("queue_depth", "workqueue depth", "#8a4fd3"),)),
    ]
    svg = [f'<svg viewBox="0 0 {W} {H}" width="{W}" height="{H}" '
           f'role="img" aria-label="scheduler throughput over time">']
    for pi, (unit, series) in enumerate(panels):
        top = mt + pi * (ph + gap)
        ymax = max(max(c[k] for c in curve) for k, _, _ in series) or 1.0
        ymax *= 1.1
        for gi in range(3):
            gy = top + ph - gi * ph / 2
            svg.append(f'<line x1="{ml}" y1="{gy:.1f}" x2="{ml + iw}" '
                       f'y2="{gy:.1f}" stroke="#e4e3de"/>'
                       f'<text x="{ml - 6}" y="{gy + 3.5:.1f}" '
                       f'text-anchor="end" class="vt">'
                       f'{ymax * gi / 2:.0f}</text>')
        svg.append(f'<text x="{ml}" y="{top - 3}" class="vt">{unit}</text>')
        for key, label, color in series:
            pts = " ".join(
                f'{ml + c["t"] / x1 * iw:.1f},'
                f'{top + ph - min(1.0, c[key] / ymax) * ph:.1f}'
                for c in curve)
            ly = top + ph - min(1.0, curve[-1][key] / ymax) * ph
            svg.append(f'<polyline points="{pts}" fill="none" '
                       f'stroke="{color}" stroke-width="2" '
                       f'stroke-linejoin="round"/>'
                       f'<text x="{ml + iw + 6}" y="{ly + 3.5:.1f}" '
                       f'class="vl" fill="{color}">{label}</text>')
    for tx in range(0, 5):
        t = x1 * tx / 4
        svg.append(f'<text x="{ml + t / x1 * iw:.1f}" y="{H - 6}" '
                   f'text-anchor="middle" class="vt">{t:.0f}s</text>')
    svg.append("</svg>")
    step = max(1, len(curve) // 40)
    rows = "".join(
        f'<tr><td>{c["t"]}</td><td>{c["binds_per_s"]}</td>'
        f'<td>{c["reconciles_per_s"]}</td><td>{c["events_per_s"]}</td>'
        f'<td>{c["queue_depth"]}</td></tr>' for c in curve[::step])
    return ("".join(svg)
            + "<details><summary>data table</summary><table>"
              "<tr><th>t (s)</th><th>binds/s</th><th>reconciles/s</th>"
              "<th>events/s</th><th>qdepth</th></tr>"
            + rows + "</table></details>")


def _fleet_sections(report: dict) -> str:
    latency = report.get("reconcile_latency") or {}
    lat_rows = "".join(
        f"<tr><td>{c}</td>"
        + "".join(f"<td>{p['ms']}</td>" for p in v["curve"])
        + f"<td>{v['max_ms']}</td><td>{v['n']}</td>"
          f"<td>{v['queue_age_p99_ms']}</td></tr>"
        for c, v in sorted(latency.items()))
    pct_hdr = "".join(
        f"<th>p{p['pct']} (ms)</th>"
        for p in (next(iter(latency.values()))["curve"] if latency else []))
    slowest = report.get("slowest_reconcile_by_controller") or {}
    slow_rows = "".join(
        f"<tr><td>{c}</td><td>{v['duration_ms']}</td>"
        f"<td>{v['trace_id']}</td></tr>"
        for c, v in sorted(slowest.items()))
    wf = "\n".join(report.get("slowest_reconcile_waterfall")
                   or ["(no sampled reconcile traces)"])
    stuck = report.get("stuck_keys") or []
    stuck_html = ("<p>none</p>" if not stuck else _kv_table(
        {f"{s['controller']} {s['key']}": f"{s['failures']} failures"
         for s in stuck}))
    ab = report.get("event_reps") or {}
    if ab:
        med = ab.get("median") or {}
        ab_rows = "".join(
            f"<tr><td>{m}</td>"
            f"<td>{(med.get(m) or {}).get('reconcile_p99_ms')}</td>"
            f"<td>{(med.get(m) or {}).get('binds_per_s')}</td>"
            f"<td>{(med.get(m) or {}).get('scan_p99_ms')}</td>"
            f"<td>{(med.get(m) or {}).get('deduped_total')}</td></tr>"
            for m in ("event",))
        ab_html = (
            "<table><tr><th>mode (median of reps)</th>"
            "<th>reconcile p99 (ms)</th><th>binds/s</th>"
            "<th>scan p99 (ms)</th><th>deduped</th></tr>"
            f"{ab_rows}</table>"
            + _kv_table({
                "dedup engaged": ab.get("dedup_engaged"),
                "spread (trimmed)":
                    f"{ab.get('spread')} (max {ab.get('spread_max')})",
                "attempt": ab.get("attempt"),
            }))
    else:
        ab_html = "<p>(throughput reps disabled: ab_reps=0)</p>"
    return f"""<style>.vt{{font:10px sans-serif;fill:#52514e}}
.vl{{font:11px sans-serif}}</style>
<h2>fleet</h2>{_kv_table(report.get("fleet") or {})}
<h2>phases (s)</h2>{_kv_table(report.get("phases") or {})}
<h2>per-controller reconcile latency</h2>
<table><tr><th>controller</th>{pct_hdr}<th>max (ms)</th><th>n</th>
<th>queue-age p99 (ms)</th></tr>{lat_rows}</table>
{_fleet_latency_svg(latency)}
<h2>scheduler throughput</h2>{_kv_table(report.get("scheduler") or {})}
{_fleet_throughput_svg(report.get("throughput_curve") or [])}
<h2>slowest reconcile per controller (exemplar → trace)</h2>
<table><tr><th>controller</th><th>ms</th><th>trace_id</th></tr>
{slow_rows}</table>
<pre>{wf}</pre>
<h2>event plane</h2>{_kv_table(report.get("events") or {})}
<h2>event-carried delivery (dedup / backstop accounting)</h2>
{_kv_table(report.get("dedup") or {})}
<h2>event-plane throughput reps</h2>
{ab_html}
<h2>stuck keys</h2>{stuck_html}
<h2>invariants</h2>{_invariants_table(report.get("invariants") or {})}"""


def write_html_report(report: dict, path: str) -> None:
    """Scenario-aware HTML report (reference analog: test/stress
    report.go). Each scenario renders ITS OWN sections — an overload or
    preemption report no longer renders the churn phase tables empty
    (which read as "0 ms, nothing happened")."""
    scenario = report.get("scenario") or (
        "churn" if "create_to_ready_ms" in report else "unknown")
    if scenario == "churn":
        body = _churn_sections(report)
    elif scenario == "overload":
        body = _overload_sections(report)
    elif scenario == "preemption":
        body = _preemption_sections(report)
    elif scenario == "autoscale":
        body = _autoscale_sections(report)
    elif scenario == "kvstream":
        body = _kvstream_sections(report)
    elif scenario == "fleet":
        body = _fleet_sections(report)
    elif scenario == "topoflip":
        body = _topoflip_sections(report)
    else:
        body = f"<pre>{json.dumps(report, indent=2)}</pre>"
    tr = report.get("trace")
    if tr:
        wf = "\n".join(tr.get("waterfall") or ["(no sampled traces)"])
        body += (f"<h2>slowest-request waterfall (hop coverage: "
                 f"{tr.get('hop_coverage')})</h2><pre>{wf}</pre>")
    html = f"""<!doctype html><html><head><meta charset="utf-8">
<title>rbg-tpu stress report — {scenario}</title>
<style>body{{font-family:sans-serif;margin:2rem}}table{{border-collapse:collapse;margin-bottom:1rem}}
td,th{{border:1px solid #999;padding:4px 10px;text-align:right}}
th{{background:#eee}}td:first-child{{text-align:left}}</style></head><body>
<h1>rbg-tpu stress report — scenario: {scenario}</h1>
<p>config: {json.dumps(report.get("config", {}))}</p>
{body}
</body></html>"""
    with open(path, "w") as f:
        f.write(html)


if __name__ == "__main__":
    import sys
    sys.exit(main())
