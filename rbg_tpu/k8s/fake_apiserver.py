"""In-repo fake of the Kubernetes API server REST semantics.

No cluster exists in this environment, so the K8s backend is tested against
this fake the way the reference tests controllers against envtest
(SURVEY.md §4 tier 2: real etcd+apiserver, synthetic pod status). It
implements the exact subset the backend's client speaks:

* CRUD on ``/api/v1/namespaces/{ns}/pods`` and ``/api/v1/nodes``
* optimistic concurrency: PUT with a stale ``metadata.resourceVersion``
  → 409 Conflict; POST on an existing name → 409
* ``labelSelector`` equality filtering on LIST
* JSON merge PATCH (``application/merge-patch+json``)
* graceful DELETE: sets ``deletionTimestamp`` and lets the node agent
  finalize (grace 0 → immediate removal)
* JSON-lines WATCH with ``resourceVersion`` resumption
* a kwok-style **node agent** (same role as the reference's kwok fake
  nodes, ``test/stress/main.go:45``): resolves the hostname selector,
  binds ``spec.nodeName``, walks pods Pending→Running(Ready) after
  ``ready_delay``, acks image patches by bumping restartCount, honors
  run-to-completion pods (→ Succeeded).
"""

from __future__ import annotations

import copy
import json
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from rbg_tpu.api import constants as C
from rbg_tpu.k8s import translate as T


def _match_selector(labels: Dict[str, str], selector: str) -> bool:
    if not selector:
        return True
    for req in selector.split(","):
        req = req.strip()
        if not req:
            continue
        if "!=" in req:
            k, v = req.split("!=", 1)
            if labels.get(k.strip()) == v.strip():
                return False
        elif "=" in req:
            k, v = req.split("=", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        else:  # bare key = exists
            if req not in labels:
                return False
    return True


class _State:
    """Object store + watch log, shared across handler threads."""

    def __init__(self):
        self.lock = threading.Condition()
        self.rv = 0
        self.pods: Dict[Tuple[str, str], dict] = {}
        self.nodes: Dict[str, dict] = {}
        # Watch replay log: (rv, type, snapshot). Bounded — and when it
        # trims, ``floor`` records the oldest retained rv so a watcher
        # resuming from before the gap gets a 410-style ERROR (real
        # apiserver semantics after etcd compaction) instead of silently
        # missing events. Before the fix, a slow watcher at burst scale
        # lost events with no signal at all.
        self.log: List[Tuple[int, str, dict]] = []
        self.floor = 0

    def bump(self) -> str:
        self.rv += 1
        return str(self.rv)

    def record(self, ev_type: str, obj: dict, kind: str = "Pod"):
        self.log.append((int(obj["metadata"]["resourceVersion"]),
                         ev_type, kind, copy.deepcopy(obj)))
        if len(self.log) > self.max_log:
            del self.log[:max(1, self.max_log // 4)]
            self.floor = self.log[0][0]
        self.lock.notify_all()

    max_log = 4096

    def compact(self, keep_last: int = 1):
        """Chaos/test hook: force-expire the watch history (etcd
        compaction analog) so resumers must take the 410 path."""
        with self.lock:
            if len(self.log) > keep_last:
                del self.log[:-keep_last]
            if self.log:
                self.floor = self.log[0][0]
            else:
                self.floor = self.rv
            self.lock.notify_all()


class FakeK8sApiServer:
    def __init__(self, ready_delay: float = 0.0, token: str = "",
                 agent: bool = True):
        self.state = _State()
        self.ready_delay = ready_delay
        self.token = token
        self._agent_enabled = agent
        self._stop = threading.Event()
        self._watch_gen = 0
        self._watch_paused = False
        self.fail_filter = None     # fn(pod_json) -> bool: walk to Failed
        state = self.state
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive (Content-Length is always set, chunked streams
            # self-terminate): a syncing backend reuses ONE connection per
            # worker instead of a TCP connect + handler-thread spawn per
            # pod operation — the dominant cost at burst scale.
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence
                pass

            # ---- helpers ----

            def _send(self, code: int, body: dict | None = None):
                data = json.dumps(body or {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def _auth_ok(self) -> bool:
                if not server.token:
                    return True
                return (self.headers.get("Authorization", "")
                        == f"Bearer {server.token}")

            def _route(self):
                u = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(u.query))
                parts = [p for p in u.path.split("/") if p]
                return parts, q

            # ---- verbs ----

            def do_GET(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                parts, q = self._route()
                # /api/v1/nodes[/name]
                if parts[:3] == ["api", "v1", "nodes"]:
                    if len(parts) == 3 and q.get("watch") == "true":
                        return self._watch("", q, kind="Node")
                    with state.lock:
                        if len(parts) == 4:
                            n = state.nodes.get(parts[3])
                            return (self._send(200, n) if n
                                    else self._send(404, {"message": "not found"}))
                        items = [n for n in state.nodes.values()
                                 if _match_selector(
                                     n["metadata"].get("labels", {}),
                                     q.get("labelSelector", ""))]
                        return self._send(200, {"kind": "NodeList",
                                                "items": copy.deepcopy(items)})
                # /api/v1/[namespaces/{ns}/]pods[/name]
                ns, name = self._pod_path(parts)
                if ns is None:
                    return self._send(404, {"message": "unknown path"})
                if q.get("watch") == "true":
                    return self._watch(ns, q)
                with state.lock:
                    if name:
                        p = state.pods.get((ns, name))
                        return (self._send(200, copy.deepcopy(p)) if p
                                else self._send(404, {"message": "not found"}))
                    items = [p for (pns, _), p in sorted(state.pods.items())
                             if (not ns or pns == ns)
                             and _match_selector(
                                 p["metadata"].get("labels", {}),
                                 q.get("labelSelector", ""))]
                    return self._send(200, {
                        "kind": "PodList",
                        "metadata": {"resourceVersion": str(state.rv)},
                        "items": copy.deepcopy(items)})

            def _pod_path(self, parts):
                # api/v1/pods | api/v1/namespaces/{ns}/pods[/{name}[/status]]
                if parts[:3] == ["api", "v1", "pods"]:
                    return "", ""
                if (len(parts) >= 5 and parts[:3] == ["api", "v1", "namespaces"]
                        and parts[4] == "pods"):
                    name = parts[5] if len(parts) > 5 else ""
                    return parts[3], name
                return None, None

            def _watch(self, ns: str, q: dict, kind: str = "Pod"):
                sel = q.get("labelSelector", "")
                since = int(q.get("resourceVersion", "0") or 0)
                deadline = time.monotonic() + float(q.get("timeoutSeconds", 30))
                gen = server._watch_gen
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def emit(ev_type, obj):
                    line = json.dumps({"type": ev_type, "object": obj}) + "\n"
                    data = line.encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()

                def end_stream():
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()

                def matches(o):
                    return ((not ns
                             or o["metadata"].get("namespace") == ns)
                            and _match_selector(
                                o["metadata"].get("labels", {}), sel))

                try:
                    if since == 0:
                        # rv=0 (k8s semantics): snapshot of current state,
                        # then future events — never a log replay, which
                        # would be incomplete after any trim.
                        with state.lock:
                            objs = (state.nodes.values() if kind == "Node"
                                    else state.pods.values())
                            snap = [copy.deepcopy(p) for p in objs
                                    if matches(p)]
                            since = state.rv
                        for o in snap:
                            emit("ADDED", o)
                    while not server._stop.is_set():
                        if server._watch_gen != gen:
                            break  # kill_watches(): clean EOF, client reconnects
                        with state.lock:
                            if server._watch_paused:
                                state.lock.wait(0.2)
                                continue
                            if since + 1 < state.floor:
                                # History compacted past the resume point:
                                # the 410 signal (as an ERROR event, the
                                # apiserver's in-stream form).
                                batch = None
                            else:
                                batch = [(rv, t, o)
                                         for (rv, t, k, o) in state.log
                                         if rv > since and k == kind
                                         and matches(o)]
                            if batch == []:
                                remaining = deadline - time.monotonic()
                                if remaining <= 0:
                                    break
                                state.lock.wait(min(remaining, 0.5))
                                continue
                        if batch is None:
                            emit("ERROR", {"kind": "Status", "code": 410,
                                           "reason": "Expired",
                                           "metadata": {}})
                            break
                        for rv, t, o in batch:
                            emit(t, o)
                            since = rv
                    end_stream()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass

            def do_POST(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                parts, _ = self._route()
                body = self._body()
                if parts[:3] == ["api", "v1", "nodes"]:
                    with state.lock:
                        name = body["metadata"]["name"]
                        ev = ("MODIFIED" if name in state.nodes
                              else "ADDED")
                        body["metadata"]["resourceVersion"] = state.bump()
                        state.nodes[name] = body
                        state.record(ev, body, kind="Node")
                        return self._send(201, body)
                ns, _ = self._pod_path(parts)
                if ns is None:
                    return self._send(404, {"message": "unknown path"})
                with state.lock:
                    name = body["metadata"]["name"]
                    if (ns, name) in state.pods:
                        return self._send(409, {"message": "already exists"})
                    meta = body["metadata"]
                    meta["namespace"] = ns
                    meta["uid"] = str(uuid.uuid4())
                    meta["resourceVersion"] = state.bump()
                    meta["creationTimestamp"] = time.time()
                    body.setdefault("status", {"phase": "Pending"})
                    state.pods[(ns, name)] = body
                    state.record("ADDED", body)
                    out = copy.deepcopy(body)
                server._agent_kick()
                return self._send(201, out)

            def do_PUT(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                parts, _ = self._route()
                body = self._body()
                ns, name = self._pod_path(parts)
                status_sub = False
                if ns is not None and len(parts) > 6 and parts[6] == "status":
                    status_sub = True
                if ns is None or not name:
                    return self._send(404, {"message": "unknown path"})
                with state.lock:
                    cur = state.pods.get((ns, name))
                    if cur is None:
                        return self._send(404, {"message": "not found"})
                    sent_rv = body.get("metadata", {}).get("resourceVersion")
                    if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                        return self._send(409, {"message": "conflict"})
                    if status_sub:
                        cur["status"] = body.get("status", {})
                    else:
                        preserved = {k: cur["metadata"][k]
                                     for k in ("uid", "namespace",
                                               "creationTimestamp")
                                     if k in cur["metadata"]}
                        cur["spec"] = body.get("spec", cur["spec"])
                        cur["metadata"] = {**body.get("metadata", {}),
                                           **preserved}
                        cur["status"] = cur.get("status", {})
                    cur["metadata"]["resourceVersion"] = state.bump()
                    state.record("MODIFIED", cur)
                    out = copy.deepcopy(cur)
                server._agent_kick()
                return self._send(200, out)

            def do_PATCH(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                parts, _ = self._route()
                patch = self._body()
                ns, name = self._pod_path(parts)
                if ns is None or not name:
                    return self._send(404, {"message": "unknown path"})

                def merge(dst, src):
                    for k, v in src.items():
                        if v is None:
                            dst.pop(k, None)
                        elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                            merge(dst[k], v)
                        elif (isinstance(v, list) and k == "containers"
                              and isinstance(dst.get(k), list)):
                            # Strategic-merge-lite: containers merge by name.
                            by_name = {c.get("name"): c for c in dst[k]}
                            for c in v:
                                tgt = by_name.get(c.get("name"))
                                if tgt is not None:
                                    merge(tgt, c)
                                else:
                                    dst[k].append(c)
                        else:
                            dst[k] = copy.deepcopy(v)

                with state.lock:
                    cur = state.pods.get((ns, name))
                    if cur is None:
                        return self._send(404, {"message": "not found"})
                    merge(cur, patch)
                    cur["metadata"]["resourceVersion"] = state.bump()
                    state.record("MODIFIED", cur)
                    out = copy.deepcopy(cur)
                server._agent_kick()
                return self._send(200, out)

            def do_DELETE(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                parts, q = self._route()
                ns, name = self._pod_path(parts)
                if ns is None or not name:
                    return self._send(404, {"message": "unknown path"})
                grace = int(q.get("gracePeriodSeconds", "0") or 0)
                with state.lock:
                    cur = state.pods.get((ns, name))
                    if cur is None:
                        return self._send(404, {"message": "not found"})
                    if grace <= 0:
                        state.pods.pop((ns, name))
                        cur["metadata"]["resourceVersion"] = state.bump()
                        state.record("DELETED", cur)
                    else:
                        cur["metadata"]["deletionTimestamp"] = time.time()
                        cur["metadata"]["resourceVersion"] = state.bump()
                        state.record("MODIFIED", cur)
                    out = copy.deepcopy(cur)
                server._agent_kick()
                return self._send(200, out)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        self._threads: List[threading.Thread] = []
        self._agent_wake = threading.Event()

    # ---- lifecycle ----

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def start(self) -> "FakeK8sApiServer":
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="fake-apiserver", daemon=True)
        t.start()
        self._threads.append(t)
        if self._agent_enabled:
            a = threading.Thread(target=self._agent_loop,
                                 name="fake-node-agent", daemon=True)
            a.start()
            self._threads.append(a)
        return self

    def stop(self):
        self._stop.set()
        self._agent_wake.set()
        with self.state.lock:
            self.state.lock.notify_all()
        self._httpd.shutdown()
        self._httpd.server_close()
        # Reap the listener + agent threads so a stopped fake leaves no
        # ambient load behind for later tests (bounded: both loops check
        # _stop within ~0.2 s).
        for t in self._threads:
            t.join(timeout=2.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---- node agent (kwok equivalent) ----

    def _agent_kick(self):
        self._agent_wake.set()

    def kill_watches(self):
        """Chaos hook: close every active watch stream (clean EOF) —
        clients must reconnect at their bookmarked rv without losing
        events (load-balancer idle reset / apiserver rolling restart)."""
        with self.state.lock:
            self._watch_gen += 1
            self.state.lock.notify_all()

    def compact(self, keep_last: int = 1):
        """Chaos hook: expire watch history (etcd compaction) — resumers
        behind the floor get the 410 ERROR and must full-relist."""
        self.state.compact(keep_last)

    def pause_watches(self, paused: bool):
        """Chaos hook: freeze event delivery on every watch stream (the
        'watch went dark' window) without closing them — deterministic
        setup for the compaction-while-dark 410 drill."""
        with self.state.lock:
            self._watch_paused = paused
            self.state.lock.notify_all()

    def add_node(self, name: str, labels: Optional[Dict[str, str]] = None,
                 address: str = "127.0.0.1", pods: int = 64, tpu: int = 0):
        """Test helper: register a (fake) node directly."""
        node = {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": name, "labels": labels or {}},
            "status": {
                "capacity": {"pods": str(pods),
                             **({T.TPU_RESOURCE: str(tpu)} if tpu else {})},
                "addresses": [{"type": "InternalIP", "address": address}],
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }
        with self.state.lock:
            ev = "MODIFIED" if name in self.state.nodes else "ADDED"
            node["metadata"]["resourceVersion"] = self.state.bump()
            self.state.nodes[name] = node
            self.state.record(ev, node, kind="Node")

    # ---- node disruption lifecycle (GKE maintenance / spot preemption) ----

    def _slice_nodes(self, slice_id: str) -> List[str]:
        with self.state.lock:
            return [name for name, n in self.state.nodes.items()
                    if (n["metadata"].get("labels", {})
                        .get(T.LABEL_GKE_NODEPOOL) == slice_id)]

    def _set_node_condition(self, name: str, cond_type: str, status: str,
                            annotations: Optional[Dict[str, str]] = None):
        with self.state.lock:
            node = self.state.nodes.get(name)
            if node is None:
                return
            conds = node.setdefault("status", {}).setdefault("conditions", [])
            for c in conds:
                if c.get("type") == cond_type:
                    c["status"] = status
                    break
            else:
                conds.append({"type": cond_type, "status": status})
            if annotations:
                node["metadata"].setdefault("annotations", {}).update(
                    annotations)
            node["metadata"]["resourceVersion"] = self.state.bump()
            self.state.record("MODIFIED", node, kind="Node")

    def set_maintenance(self, slice_id: str, deadline_s: float,
                        now: Optional[float] = None) -> List[str]:
        """Advance-notice maintenance against EVERY host of a slice (node
        pool): a MaintenancePending condition + deadline annotation. The
        control plane's node sync turns this into the disruption
        controller's migrate-before-deadline path."""
        now = time.time() if now is None else now
        names = self._slice_nodes(slice_id)
        for name in names:
            self._set_node_condition(
                name, T.COND_MAINTENANCE, "True",
                {T.ANN_MAINT_DEADLINE: f"{now + deadline_s:.3f}"})
        return names

    def preempt_slice(self, slice_id: str,
                      hosts: Optional[List[str]] = None) -> List[str]:
        """No-notice spot preemption: the hosts (default: the whole node
        pool — one ICI domain always goes together) flip NotReady +
        Preempted and every pod bound to them fails with reason Preempted
        + a DisruptionTarget condition (what GKE leaves behind)."""
        names = self._slice_nodes(slice_id)
        if hosts is not None:
            names = [n for n in names if n in hosts]
        for name in names:
            self._set_node_condition(name, T.COND_PREEMPTED, "True")
            self._set_node_condition(name, "Ready", "False")
        with self.state.lock:
            for key, pod in list(self.state.pods.items()):
                if pod.get("spec", {}).get("nodeName") not in names:
                    continue
                st = pod.setdefault("status", {})
                if st.get("phase") in ("Failed", "Succeeded"):
                    continue
                st["phase"] = "Failed"
                st["reason"] = "Preempted"
                st.setdefault("conditions", []).append(
                    {"type": "DisruptionTarget", "status": "True",
                     "reason": "Preempted"})
                for c in st.get("containerStatuses", []):
                    c["state"] = {"terminated": {"exitCode": 137}}
                pod["metadata"]["resourceVersion"] = self.state.bump()
                self.state.record("MODIFIED", pod)
        return names

    def _agent_loop(self):
        while not self._stop.is_set():
            self._agent_wake.wait(timeout=0.2)
            self._agent_wake.clear()
            if self.ready_delay:
                time.sleep(self.ready_delay)
            with self.state.lock:
                for key, pod in list(self.state.pods.items()):
                    if self._agent_step(pod):
                        pod["metadata"]["resourceVersion"] = self.state.bump()
                        self.state.record("MODIFIED", pod)
                # Finalize gracefully-deleted pods.
                for key, pod in list(self.state.pods.items()):
                    if pod["metadata"].get("deletionTimestamp") is not None:
                        self.state.pods.pop(key)
                        pod["metadata"]["resourceVersion"] = self.state.bump()
                        self.state.record("DELETED", pod)

    def _agent_step(self, pod: dict) -> bool:
        """One kubelet-ish observation of a pod. Returns True if changed."""
        spec = pod.get("spec", {})
        meta = pod.get("metadata", {})
        st = pod.setdefault("status", {"phase": "Pending"})
        # Bind: resolve the hostname selector (plane pins placement).
        def node_ready(n: dict) -> bool:
            conds = {c.get("type"): c.get("status")
                     for c in n.get("status", {}).get("conditions", [])}
            return conds.get("Ready", "True") == "True"

        if not spec.get("nodeName"):
            host = (spec.get("nodeSelector") or {}).get(T.LABEL_HOSTNAME)
            if host and host in self.state.nodes:
                spec["nodeName"] = host
            else:
                live = sorted(n for n, nd in self.state.nodes.items()
                              if node_ready(nd))
                if not live:
                    return False
                spec["nodeName"] = live[0]
        node = self.state.nodes.get(spec["nodeName"])
        # A NotReady host has no kubelet: pods bound there make NO
        # progress (a preempted node can never run its pods — without
        # this, a replacement gang could 'start' on vanished hardware).
        if node is not None and not node_ready(node):
            return False
        if st.get("phase") == "Pending":
            if self.fail_filter is not None and self.fail_filter(pod):
                st["phase"] = "Failed"
                st["reason"] = "FakeAgentInjected"
                return True
            run_once = (meta.get("annotations", {}).get(
                f"{C.DOMAIN}/run-to-completion") == "true")
            st["phase"] = "Succeeded" if run_once else "Running"
            st["startTime"] = time.time()
            addr = "127.0.0.1"
            if node:
                for a in node["status"].get("addresses", []):
                    if a.get("type") == "InternalIP":
                        addr = a["address"]
            st["podIP"] = addr
            st["conditions"] = [{"type": "Ready",
                                 "status": "False" if run_once else "True"}]
            st["containerStatuses"] = [
                {"name": c["name"], "image": c["image"], "restartCount": 0,
                 "state": {"running": {}} if not run_once
                 else {"terminated": {"exitCode": 0}}}
                for c in spec.get("containers", [])]
            return True
        if st.get("phase") == "Running":
            # Ack image patches: restart the container on the new image.
            changed = False
            statuses = st.setdefault("containerStatuses", [])
            by_name = {cs.get("name"): cs for cs in statuses}
            for c in spec.get("containers", []):
                cs = by_name.get(c["name"])
                if cs is None:
                    continue
                if cs.get("image") != c["image"]:
                    cs["image"] = c["image"]
                    cs["restartCount"] = int(cs.get("restartCount", 0)) + 1
                    changed = True
            return changed
        return False
