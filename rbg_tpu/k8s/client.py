"""Minimal Kubernetes REST client.

Speaks the exact wire protocol the fake API server (and a real apiserver)
serves: JSON bodies, ``resourceVersion`` optimistic concurrency (409 →
``Conflict``), ``labelSelector`` list filtering, and JSON-lines watch
streams. Only the surface the pod backend needs — this replaces the
reference's generated clientset (SURVEY.md §2 #26) the same way
``api/serde.py`` replaces its deepcopy/apply-configuration machinery.

Auth: optional bearer token (the in-cluster ``/var/run/secrets/...`` token
path or a literal). TLS is delegated to ``ssl`` default context when the
URL is https.
"""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class Conflict(ApiError):
    pass


class NotFound(ApiError):
    pass


def _raise(status: int, body: str):
    if status == 409:
        raise Conflict(status, body)
    if status == 404:
        raise NotFound(status, body)
    raise ApiError(status, body)


class KubeClient:
    def __init__(self, base_url: str, token: str = "",
                 timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    # ---- plumbing ----

    def _headers(self, extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        h = {"Content-Type": "application/json",
             "Accept": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        h.update(extra or {})
        return h

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[Dict[str, str]] = None,
                content_type: str = "application/json") -> dict:
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers=self._headers({"Content-Type": content_type}))
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            _raise(e.code, e.read().decode(errors="replace")[:400])
        except (urllib.error.URLError, socket.timeout) as e:
            raise ApiError(0, f"{type(e).__name__}: {e}")
        return json.loads(payload) if payload else {}

    # ---- pods ----

    def list_pods(self, namespace: str = "",
                  label_selector: str = "") -> List[dict]:
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        params = {"labelSelector": label_selector} if label_selector else None
        return self.request("GET", path, params=params).get("items", [])

    def get_pod(self, namespace: str, name: str) -> dict:
        return self.request("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def create_pod(self, namespace: str, pod: dict) -> dict:
        return self.request("POST", f"/api/v1/namespaces/{namespace}/pods",
                            body=pod)

    def update_pod(self, namespace: str, name: str, pod: dict) -> dict:
        return self.request("PUT", f"/api/v1/namespaces/{namespace}/pods/{name}",
                            body=pod)

    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        """Strategic merge patch: lists with patchMergeKey (containers)
        merge BY NAME instead of wholesale replacement — required for
        image-only in-place updates (a plain RFC 7386 merge patch would
        replace the whole containers array and be rejected as a pod-spec
        mutation)."""
        return self.request(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}",
            body=patch, content_type="application/strategic-merge-patch+json")

    def delete_pod(self, namespace: str, name: str,
                   grace_period_seconds: int = 0) -> None:
        try:
            self.request("DELETE",
                         f"/api/v1/namespaces/{namespace}/pods/{name}",
                         params={"gracePeriodSeconds": str(grace_period_seconds)})
        except NotFound:
            pass

    # ---- nodes ----

    def list_nodes(self, label_selector: str = "") -> List[dict]:
        params = {"labelSelector": label_selector} if label_selector else None
        return self.request("GET", "/api/v1/nodes", params=params).get("items", [])

    # ---- watch ----

    def watch_pods(self, namespace: str = "", label_selector: str = "",
                   resource_version: str = "0",
                   timeout_s: float = 30.0) -> Iterator[Tuple[str, dict]]:
        """Yield (event_type, pod) from a JSON-lines watch stream. Returns
        when the server closes the stream (bookmark your own last
        resourceVersion and reconnect)."""
        import http.client

        u = urllib.parse.urlparse(self.base_url)
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        params = {"watch": "true", "resourceVersion": resource_version,
                  "timeoutSeconds": str(int(timeout_s))}
        if label_selector:
            params["labelSelector"] = label_selector
        path += "?" + urllib.parse.urlencode(params)
        conn_cls = (http.client.HTTPSConnection if u.scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(u.hostname, u.port, timeout=timeout_s + 5)
        try:
            conn.request("GET", path, headers=self._headers())
            resp = conn.getresponse()
            if resp.status != 200:
                _raise(resp.status, resp.read().decode(errors="replace")[:400])
            while True:
                line = resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                yield ev["type"], ev["object"]
        except (http.client.HTTPException, OSError):
            return
        finally:
            conn.close()
