"""Minimal Kubernetes REST client.

Speaks the exact wire protocol the fake API server (and a real apiserver)
serves: JSON bodies, ``resourceVersion`` optimistic concurrency (409 →
``Conflict``), ``labelSelector`` list filtering, and JSON-lines watch
streams. Only the surface the pod backend needs — this replaces the
reference's generated clientset (SURVEY.md §2 #26) the same way
``api/serde.py`` replaces its deepcopy/apply-configuration machinery.

Auth: optional bearer token (the in-cluster ``/var/run/secrets/...`` token
path or a literal). TLS is delegated to ``ssl`` default context when the
URL is https.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import urllib.parse
from typing import Dict, Iterator, List, Optional, Tuple


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class Conflict(ApiError):
    pass


class NotFound(ApiError):
    pass


def _raise(status: int, body: str):
    if status == 409:
        raise Conflict(status, body)
    if status == 404:
        raise NotFound(status, body)
    raise ApiError(status, body)


class KubeClient:
    def __init__(self, base_url: str, token: str = "",
                 timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self._local = threading.local()  # per-thread keep-alive connection

    # ---- plumbing ----

    def _headers(self, extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        h = {"Content-Type": "application/json",
             "Accept": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        h.update(extra or {})
        return h

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            u = urllib.parse.urlparse(self.base_url)
            cls = (http.client.HTTPSConnection if u.scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(u.hostname, u.port, timeout=self.timeout)
            self._local.conn = conn
        return conn

    def _drop_conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[Dict[str, str]] = None,
                content_type: str = "application/json") -> dict:
        """One API round trip over a PER-THREAD keep-alive connection
        (a fresh TCP connect per call costs a server handler-thread spawn
        each time — the dominant burst-scale overhead). A stale kept-alive
        socket (server restarted / idle-closed) is retried once on a fresh
        connection; HTTP errors are not retried."""
        if params:
            path += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        headers = self._headers({"Content-Type": content_type})
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                status = resp.status
                break
            except (ConnectionError, http.client.RemoteDisconnected,
                    http.client.CannotSendRequest) as e:
                # Pre-response connection death — the idle keep-alive
                # socket went stale. Retrying is safe-ish (the server may
                # have executed a delivered non-idempotent request, which
                # surfaces as a 409 the callers already handle). A
                # TIMEOUT is deliberately NOT retried: the request may be
                # mid-execution and a blind re-send would double it while
                # doubling the latency of a down server.
                self._drop_conn()
                if attempt:
                    raise ApiError(0, f"{type(e).__name__}: {e}")
            except (socket.timeout, OSError,
                    http.client.HTTPException) as e:
                self._drop_conn()
                raise ApiError(0, f"{type(e).__name__}: {e}")
        if status >= 400:
            _raise(status, payload.decode(errors="replace")[:400])
        return json.loads(payload) if payload else {}

    # ---- pods ----

    def list_pods(self, namespace: str = "",
                  label_selector: str = "") -> List[dict]:
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        params = {"labelSelector": label_selector} if label_selector else None
        return self.request("GET", path, params=params).get("items", [])

    def get_pod(self, namespace: str, name: str) -> dict:
        return self.request("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def create_pod(self, namespace: str, pod: dict) -> dict:
        return self.request("POST", f"/api/v1/namespaces/{namespace}/pods",
                            body=pod)

    def update_pod(self, namespace: str, name: str, pod: dict) -> dict:
        return self.request("PUT", f"/api/v1/namespaces/{namespace}/pods/{name}",
                            body=pod)

    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        """Strategic merge patch: lists with patchMergeKey (containers)
        merge BY NAME instead of wholesale replacement — required for
        image-only in-place updates (a plain RFC 7386 merge patch would
        replace the whole containers array and be rejected as a pod-spec
        mutation)."""
        return self.request(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}",
            body=patch, content_type="application/strategic-merge-patch+json")

    def delete_pod(self, namespace: str, name: str,
                   grace_period_seconds: int = 0) -> None:
        try:
            self.request("DELETE",
                         f"/api/v1/namespaces/{namespace}/pods/{name}",
                         params={"gracePeriodSeconds": str(grace_period_seconds)})
        except NotFound:
            pass

    # ---- nodes ----

    def list_nodes(self, label_selector: str = "") -> List[dict]:
        params = {"labelSelector": label_selector} if label_selector else None
        return self.request("GET", "/api/v1/nodes", params=params).get("items", [])

    # ---- watch ----

    def watch_pods(self, namespace: str = "", label_selector: str = "",
                   resource_version: str = "0",
                   timeout_s: float = 30.0) -> Iterator[Tuple[str, dict]]:
        """Yield (event_type, pod) from a JSON-lines watch stream. Returns
        when the server closes the stream (bookmark your own last
        resourceVersion and reconnect)."""
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        yield from self._watch_stream(path, label_selector,
                                      resource_version, timeout_s)

    def watch_nodes(self, label_selector: str = "",
                    resource_version: str = "0",
                    timeout_s: float = 30.0) -> Iterator[Tuple[str, dict]]:
        """Node watch stream (same contract as watch_pods) — the
        event-carried replacement for polling list_nodes: node disruption
        state reaches the plane when it CHANGES, with the periodic full
        sync demoted to a drift backstop."""
        yield from self._watch_stream("/api/v1/nodes", label_selector,
                                      resource_version, timeout_s)

    def _watch_stream(self, path: str, label_selector: str,
                      resource_version: str,
                      timeout_s: float) -> Iterator[Tuple[str, dict]]:
        import http.client

        u = urllib.parse.urlparse(self.base_url)
        params = {"watch": "true", "resourceVersion": resource_version,
                  "timeoutSeconds": str(int(timeout_s))}
        if label_selector:
            params["labelSelector"] = label_selector
        path += "?" + urllib.parse.urlencode(params)
        conn_cls = (http.client.HTTPSConnection if u.scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(u.hostname, u.port, timeout=timeout_s + 5)
        try:
            conn.request("GET", path, headers=self._headers())
            resp = conn.getresponse()
            if resp.status != 200:
                _raise(resp.status, resp.read().decode(errors="replace")[:400])
            while True:
                line = resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                yield ev["type"], ev["object"]
        except (http.client.HTTPException, OSError):
            return
        finally:
            conn.close()
