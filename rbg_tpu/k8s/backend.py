"""K8sPodBackend — the kubelet-seam implementation that realizes plane Pods
as real Kubernetes Pods.

Division of labor (deliberately different from the reference, which IS a
K8s controller): the plane keeps its own store, controllers, and the
slice-aware gang scheduler; this backend is a *mirror* at the pod boundary —
the one object kind whose lifecycle a cluster must own. Reference analog
for what gets mirrored: ``pkg/reconciler/pod_reconciler.go:64-390`` (pod
construction) + the kubelet itself (status).

Flow:

* plane Pod scheduled (``node_name`` set)  → CREATE mirrored K8s Pod
  (GKE TPU shape, ``translate.to_k8s_pod``)
* plane in-place image update             → PATCH K8s containers (the only
  mutable pod field, matching ``pkg/inplace`` semantics)
* plane graceful delete                   → DELETE K8s pod; plane-side
  ``finalize_delete`` happens when the cluster confirms the pod is gone
* K8s pod status                          → reflected into plane
  ``pod.status`` (phase/ready/IP/restarts + in-place ack)
* K8s pod deleted out-of-band             → plane pod marked Failed
  (reason ``Deleted``) so the restart engine replaces it
* K8s TPU nodes                           → synced into plane Nodes at
  startup (labels → TpuNodeInfo) so the scheduler places on real capacity
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional, Tuple

from rbg_tpu.api import constants as C
from rbg_tpu.k8s import translate as T
from rbg_tpu.k8s.client import ApiError, Conflict, KubeClient, NotFound
from rbg_tpu.runtime.store import Event, Store
from rbg_tpu.runtime.store import Conflict as StoreConflict
from rbg_tpu.runtime.store import NotFound as StoreNotFound
from rbg_tpu.utils.locktrace import named_lock
from rbg_tpu.utils.racetrace import guard as _race_guard

log = logging.getLogger("rbg_tpu.k8s")

_SELECTOR = f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}"


@_race_guard
class K8sPodBackend:
    SYNC_WORKERS = 8

    def __init__(self, store: Store, client: KubeClient,
                 sync_nodes: bool = True):
        self.store = store
        self.client = client
        self.sync_nodes = sync_nodes
        self._stop = threading.Event()
        # Desired-state dirty sets, SHARDED by pod key: per-key ordering
        # (create → patch → delete must serialize) is preserved because a
        # key always hashes to the same worker, while different pods sync
        # in parallel — a single serial drain was the burst-scale
        # bottleneck (one REST round trip at a time for a 1200-pod burst).
        # Workers drain with retries so a flaky API server never loses an
        # operation (watch callbacks must not block).
        self._dirty = [dict() for _ in range(self.SYNC_WORKERS)]  # guarded_by[k8s.backend_dirty]
        self._wakes = [threading.Event() for _ in range(self.SYNC_WORKERS)]
        self._lock = named_lock("k8s.backend_dirty")
        # Last-known mirrored spec images, to detect in-place patches.
        self._mirrored_images: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._threads: list = []

    # ---- kubelet contract ----

    def start(self):
        if self.sync_nodes:
            self._sync_nodes()
        self.store.watch("Pod", self._on_event)
        for pod in self.store.list("Pod"):
            self._mark(pod.metadata.namespace, pod.metadata.name)
        self._adopt_orphans()
        for i in range(self.SYNC_WORKERS):
            t = threading.Thread(target=self._sync_loop, args=(i,),
                                 name=f"k8s-sync-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._reflect_loop, name="k8s-reflect",
                             daemon=True)
        t.start()
        self._threads.append(t)
        if self.sync_nodes:
            # Node disruption lifecycle (maintenance conditions, cordons,
            # preemption NotReady) must reach the plane CONTINUOUSLY, not
            # just at startup — the disruption controller's deadlines are
            # wall-clock.
            t = threading.Thread(target=self._node_loop, name="k8s-nodes",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self):
        self._stop.set()
        for w in self._wakes:
            w.set()
        # The reflector can be parked inside a watch stream for up to
        # WATCH_WINDOW_S — join past that so stop() really stops the
        # threads (a reflector outliving its plane kept mutating the store
        # and burning CPU into the NEXT test's budget).
        for t in self._threads:
            t.join(timeout=self.WATCH_WINDOW_S + 1.0)

    # ---- plane → cluster ----

    def _on_event(self, ev: Event):
        pod = ev.object
        self._mark(pod.metadata.namespace, pod.metadata.name)

    def _shard(self, key: Tuple[str, str]) -> int:
        return hash(key) % self.SYNC_WORKERS

    def _mark(self, ns: str, name: str):
        key = (ns, name)
        shard = self._shard(key)
        with self._lock:
            self._dirty[shard][key] = True
        self._wakes[shard].set()

    def _sync_loop(self, shard: int):
        wake = self._wakes[shard]
        while not self._stop.is_set():
            wake.wait(timeout=0.5)
            wake.clear()
            with self._lock:
                keys = list(self._dirty[shard])
                self._dirty[shard].clear()
            for ns, name in keys:
                try:
                    self._sync_one(ns, name)
                except (ApiError, StoreConflict) as e:
                    log.warning("k8s sync %s/%s: %s (requeued)", ns, name, e)
                    self._mark(ns, name)

    def _sync_one(self, ns: str, name: str):
        pod = self.store.get("Pod", ns, name, copy_=False)
        if pod is None:
            # Plane pod hard-deleted: remove any mirror.
            self.client.delete_pod(ns, name)
            self._mirrored_images.pop((ns, name), None)
            return
        if pod.metadata.deletion_timestamp is not None:
            try:
                self.client.get_pod(ns, name)
            except NotFound:
                self._mirrored_images.pop((ns, name), None)
                try:
                    self.store.finalize_delete("Pod", ns, name)
                except (StoreNotFound, StoreConflict):
                    pass
                return
            self.client.delete_pod(ns, name, grace_period_seconds=0)
            # DELETED arrives on the reflector; finalize then. But the
            # watch can race a short stream window — requeue a check.
            self._mark(ns, name)
            return
        if not pod.node_name:
            return  # not scheduled yet — the plane scheduler owns this
        key = (ns, name)
        desired = T.desired_images(pod)
        mirrored = self._mirrored_images.get(key)
        if mirrored is None:
            if pod.status.phase in ("Failed", "Succeeded"):
                # Terminal plane pod with no mirror (e.g. the cluster pod
                # was deleted out-of-band): never resurrect it — the
                # restart engine replaces the plane pod itself.
                return
            node = self.store.get("Node", "default", pod.node_name,
                                  copy_=False)
            body = T.to_k8s_pod(pod, node)
            try:
                self.client.create_pod(ns, body)
            except Conflict:
                # Exists (resume/adoption): adopt ONLY if the live pod is
                # this plane pod's own mirror — identity is the plane-uid
                # annotation, not the name (an older-snapshot resume can
                # collide with a later incarnation on another node).
                live = self.client.get_pod(ns, name)
                live_uid = (live.get("metadata", {}).get("annotations", {})
                            or {}).get(T.ANN_PLANE_UID)
                if live_uid != pod.metadata.uid:
                    self.client.delete_pod(ns, name)
                    self._mark(ns, name)  # recreate on the next pass
                    return
                live_imgs = {c["name"]: c.get("image", "")
                             for c in live.get("spec", {}).get("containers", [])}
                self._mirrored_images[key] = live_imgs
                mirrored = live_imgs
            else:
                self._mirrored_images[key] = desired
                return
        if mirrored != desired:
            # In-place update: image-only container patch (the single
            # mutable field, pkg/inplace inplace_update_defaults.go:76-95).
            patch = {"spec": {"containers": [
                {"name": n, "image": img} for n, img in desired.items()
                if mirrored.get(n) != img]}}
            self.client.patch_pod(ns, name, patch)
            self._mirrored_images[key] = desired

    def _adopt_orphans(self):
        """Delete mirrored pods whose plane pod no longer exists (plane
        resumed from an older snapshot, or cluster leftovers)."""
        try:
            for kpod in self.client.list_pods(label_selector=_SELECTOR):
                meta = kpod.get("metadata", {})
                ns, name = meta.get("namespace", ""), meta.get("name", "")
                pod = self.store.get("Pod", ns, name, copy_=False)
                live_uid = (meta.get("annotations", {})
                            or {}).get(T.ANN_PLANE_UID)
                if pod is None or live_uid != pod.metadata.uid:
                    # No plane pod, or a different incarnation's mirror.
                    self.client.delete_pod(ns, name)
                else:
                    live_imgs = {c["name"]: c.get("image", "") for c in
                                 kpod.get("spec", {}).get("containers", [])}
                    self._mirrored_images[(ns, name)] = live_imgs
        except ApiError as e:
            log.warning("k8s orphan scan failed: %s", e)

    # ---- cluster → plane ----

    # Per-connection watch window: short enough that stop() (which joins
    # WATCH_WINDOW_S + 1) returns promptly, long enough that idle
    # reconnects stay cheap (the stream resumes from the rv bookmark).
    WATCH_WINDOW_S = 2.0

    def _reflect_loop(self):
        rv = "0"
        while not self._stop.is_set():
            try:
                for ev_type, kpod in self.client.watch_pods(
                        label_selector=_SELECTOR, resource_version=rv,
                        timeout_s=self.WATCH_WINDOW_S):
                    if ev_type == "ERROR":
                        # Watch bookmark expired (410 Gone as an event):
                        # fall back to a full re-list.
                        rv = self._resync()
                        break
                    meta = kpod.get("metadata", {})
                    rv = meta.get("resourceVersion", rv)
                    self._reflect(ev_type, kpod)
                    if self._stop.is_set():
                        return
            except ApiError as e:
                if e.status == 410:
                    rv = self._resync()
                else:
                    log.warning("k8s watch: %s (reconnecting)", e)
                    self._stop.wait(0.5)

    def _resync(self) -> str:
        """Full re-list after watch expiry (410 Gone / etcd compaction):
        reflect every live pod and synthesize DELETED for mirrors that
        vanished while the watch was dark. Returns the list's rv."""
        try:
            live = self.client.list_pods(label_selector=_SELECTOR)
        except ApiError as e:
            log.warning("k8s resync list failed: %s", e)
            return "0"
        seen = set()
        max_rv = 0
        for kpod in live:
            meta = kpod.get("metadata", {})
            seen.add((meta.get("namespace", ""), meta.get("name", "")))
            try:
                max_rv = max(max_rv, int(meta.get("resourceVersion", 0)))
            except ValueError:
                pass
            self._reflect("MODIFIED", kpod)
        for key in list(self._mirrored_images):
            if key not in seen:
                self._reflect("DELETED", {"metadata": {
                    "namespace": key[0], "name": key[1]}})
        return str(max_rv) if max_rv else "0"

    def _reflect(self, ev_type: str, kpod: dict):
        meta = kpod.get("metadata", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
        pod = self.store.get("Pod", ns, name, copy_=False)
        if ev_type == "DELETED":
            self._mirrored_images.pop((ns, name), None)
            if pod is None:
                return
            if pod.metadata.deletion_timestamp is not None:
                try:
                    self.store.finalize_delete("Pod", ns, name)
                except (StoreNotFound, StoreConflict):
                    pass
            else:
                # Out-of-band deletion (node drain, manual kubectl): the
                # restart engine must see a dead pod and replace it.
                self._set_failed(ns, name, reason="Deleted")
            return
        if pod is None:
            return
        ref = T.reflect_status(kpod)
        self._apply_status(ns, name, ref)

    def _set_failed(self, ns: str, name: str, reason: str):
        def fn(p):
            if p.status.phase in ("Failed", "Succeeded"):
                return False
            p.status.phase = "Failed"
            p.status.ready = False
            p.status.reason = reason
            return True
        try:
            self.store.mutate("Pod", ns, name, fn, status=True)
        except (StoreNotFound, StoreConflict):
            pass

    def _apply_status(self, ns: str, name: str, ref: dict):
        from rbg_tpu.inplace.update import load_state

        def fn(p):
            changed = False
            if p.status.phase != ref["phase"]:
                p.status.phase = ref["phase"]
                changed = True
            ready = ref["ready"] and not ref["deleting"]
            if p.status.ready != ready:
                p.status.ready = ready
                changed = True
            for field, key in (("pod_ip", "pod_ip"),
                               ("reason", "reason")):
                if getattr(p.status, field) != ref[key]:
                    setattr(p.status, field, ref[key])
                    changed = True
            if ref["node_name"] and p.status.node_name != ref["node_name"]:
                p.status.node_name = ref["node_name"]
                changed = True
            if ref["start_time"] and not p.status.start_time:
                p.status.start_time = ref["start_time"]
                changed = True
            total = 0
            for cname, count in ref["container_restarts"].items():
                if p.status.container_restarts.get(cname) != count:
                    p.status.container_restarts[cname] = count
                    changed = True
                total += count
            if ref["container_restarts"] and p.status.restart_count != total:
                p.status.restart_count = total
                changed = True
            # Revision observation: first Running stamps the pod's revision
            # label; an in-place update is acknowledged once the cluster
            # reports every patched container RUNNING on its new image
            # (the FakeKubelet._ack_inplace analog, driven by real status).
            state = load_state(p)
            if state and state.get("revision"):
                wanted = state.get("images") or {}
                live = ref["running_images"]
                if (p.status.observed_revision != state["revision"]
                        and wanted
                        and all(live.get(n) == img
                                for n, img in wanted.items())):
                    p.status.observed_revision = state["revision"]
                    changed = True
            elif (ref["phase"] == "Running"
                  and not p.status.observed_revision):
                rev = p.metadata.labels.get(C.LABEL_REVISION_NAME, "")
                if rev:
                    p.status.observed_revision = rev
                    changed = True
            return changed

        try:
            self.store.mutate("Pod", ns, name, fn, status=True)
        except (StoreNotFound, StoreConflict):
            pass

    # ---- node inventory ----

    # Node inventory rides the node WATCH stream with a long drift
    # backstop: node disruption state arrives when it changes, and the
    # full re-list exists to self-heal a silently wedged stream, not to
    # carry data. (The pre-PR-12 2 s polling plane is gone with the
    # ``legacy_resync`` A/B toggle.)
    NODE_BACKSTOP_S = 60.0

    def _node_loop(self):
        # Resume the watch from the rv the initial LIST covered — a
        # rv="0" watch against a REAL apiserver starts at a server-chosen
        # point with no snapshot, silently dropping anything that landed
        # between the list and the watch registration (the same
        # list→watch gap class Store.watch(since_rv=) closes in-process).
        rv = self._sync_nodes()
        last_full = time.monotonic()
        while not self._stop.is_set():
            try:
                for ev_type, kn in self.client.watch_nodes(
                        resource_version=rv,
                        timeout_s=self.WATCH_WINDOW_S):
                    if self._stop.is_set():
                        return
                    if ev_type == "ERROR":
                        # History expired past our bookmark: full re-list
                        # and resume from the rv that list covered.
                        rv = self._sync_nodes()
                        last_full = time.monotonic()
                        break
                    meta = kn.get("metadata", {})
                    rv = meta.get("resourceVersion", rv)
                    if ev_type == "DELETED":
                        continue  # parity: the poller never deleted either
                    try:
                        self._sync_node_obj(kn)
                    except Exception:
                        log.warning("k8s node event sync failed",
                                    exc_info=True)
            except ApiError as e:
                if e.status == 410:
                    # History expired: a REAL apiserver will not snapshot
                    # current state on a rv=0 reconnect (that is
                    # fake-only), so re-list now — state changed during
                    # the dark window must not wait out the 60 s backstop.
                    rv = self._sync_nodes()
                    last_full = time.monotonic()
                else:
                    log.warning("k8s node watch: %s (reconnecting)", e)
                    self._stop.wait(0.5)
            except Exception:
                log.warning("k8s node watch failed (reconnecting)",
                            exc_info=True)
                self._stop.wait(0.5)
            if time.monotonic() - last_full >= self.NODE_BACKSTOP_S:
                try:
                    rv = self._sync_nodes()
                except Exception:
                    log.warning("k8s node backstop sync failed",
                                exc_info=True)
                last_full = time.monotonic()

    def _sync_nodes(self) -> str:
        """Import the cluster's TPU nodes as plane Nodes (idempotent): the
        scheduler then gangs slices onto real capacity. Non-TPU nodes are
        imported too (router/CPU roles need somewhere to run). Run at
        startup, from node watch events, and as a periodic drift backstop
        so node-level disruption state (maintenance conditions, preemption
        NotReady, cordons) keeps flowing; no-op when nothing changed so
        steady state emits no events. Returns the max resourceVersion the
        list covered — the gap-free resume point for the node watch."""
        try:
            knodes = self.client.list_nodes()
        except ApiError as e:
            log.warning("k8s node sync failed: %s", e)
            return "0"
        max_rv = 0
        for kn in knodes:
            try:
                max_rv = max(max_rv, int(
                    kn.get("metadata", {}).get("resourceVersion", 0)))
            except ValueError:
                pass
            self._sync_node_obj(kn)
        return str(max_rv) if max_rv else "0"

    def _sync_node_obj(self, kn: dict):
        """Reflect ONE cluster node into the plane (shared by the watch
        event path and the full-list backstop). Conflicts retry with a
        fresh read: under the old 2 s poller a lost write self-healed
        within one period, but a watch event is delivered ONCE — dropping
        it on conflict would park cluster disruption state for the whole
        60 s backstop (longer than some maintenance notice windows)."""
        from rbg_tpu.api import serde
        for _ in range(4):
            node = T.node_from_k8s(kn)
            if not node.metadata.name:
                return
            cur = self.store.get("Node", "default", node.metadata.name)
            if cur is None:
                from rbg_tpu.runtime.store import AlreadyExists
                try:
                    self.store.create(node)
                    return
                except AlreadyExists:
                    continue  # watch raced the startup/backstop list
            node.metadata = cur.metadata
            # The plane owns cordons it placed ITSELF (disruption
            # controller, marked by the cordoned-by annotation) — a
            # resync must not clear those just because the cluster
            # hasn't mirrored the bit. Every other cordon state is the
            # cluster's to set AND clear: without the marker check, an
            # operator's kubectl cordon/uncordon cycle would leave the
            # plane-side bit stuck True forever.
            if (cur.unschedulable and cur.metadata.annotations.get(
                    C.ANN_CORDONED_BY) == "disruption"):
                node.unschedulable = True
            if serde.to_dict(node) == serde.to_dict(cur):
                return
            try:
                self.store.update(node)
                return
            except StoreConflict:
                continue  # plane wrote concurrently — re-read and re-merge
        # Watch events are delivered ONCE — a drop here parks cluster
        # state for the whole backstop, so losing the retry race must at
        # least be LOUD (the operations runbook tells operators to look
        # for exactly this when drift shows up).
        log.warning("k8s node sync %s: conflict retries exhausted — "
                    "state deferred to the %ss backstop",
                    kn.get("metadata", {}).get("name", "?"),
                    self.NODE_BACKSTOP_S)
