"""Plane Pod / Node ↔ Kubernetes JSON translation (GKE TPU shaped).

Reference analog: the shared pod-template builder
(``pkg/reconciler/pod_reconciler.go:64-390``) constructs corev1 Pods from
role templates; here the plane's own Pod objects (already fully built by
the instance controller + discovery injectors) are translated to the K8s
wire form the moment they cross to a real cluster.

GKE TPU contract (SURVEY.md §7 step 5):

* chip resources: ``google.com/tpu`` in requests+limits,
* node selection: ``cloud.google.com/gke-tpu-topology`` /
  ``cloud.google.com/gke-tpu-accelerator`` labels,
* one multi-host slice == one node pool → the node-pool label IS the slice
  identity; the plane's slice-binding annotation (``ANN_SLICE_BINDING``)
  becomes REQUIRED nodeAffinity on it,
* hostNetwork for TPU pods (ICI/DCN path stays off the overlay).
"""

from __future__ import annotations

from typing import Dict, Optional

from rbg_tpu.api import constants as C
from rbg_tpu.api.pod import Node, Pod, TpuNodeInfo

# GKE well-known keys.
TPU_RESOURCE = "google.com/tpu"
LABEL_GKE_TPU_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
LABEL_GKE_TPU_ACCEL = "cloud.google.com/gke-tpu-accelerator"
LABEL_GKE_NODEPOOL = "cloud.google.com/gke-nodepool"   # slice identity
LABEL_HOSTNAME = "kubernetes.io/hostname"
# Plane-owned identity on mirrored objects.
LABEL_MANAGED_BY = f"{C.DOMAIN}/managed-by"
MANAGED_BY = "rbg-tpu"
ANN_PLANE_UID = f"{C.DOMAIN}/plane-uid"
LABEL_WORKER_INDEX = f"{C.DOMAIN}/tpu-worker-index"
# Node disruption lifecycle on the K8s wire (GKE surfaces maintenance via
# node conditions; spot preemption as an out-of-band NotReady/terminated).
COND_MAINTENANCE = "MaintenancePending"
COND_PREEMPTED = "Preempted"
ANN_MAINT_DEADLINE = f"{C.DOMAIN}/maintenance-deadline"  # unix seconds


def _container_to_k8s(c) -> dict:
    out: dict = {"name": c.name, "image": c.image}
    if c.command:
        out["command"] = list(c.command)
    if c.args:
        out["args"] = list(c.args)
    if c.env:
        out["env"] = [{"name": e.name, "value": e.value} for e in c.env]
    if c.ports:
        out["ports"] = [{"name": p.name, "containerPort": p.container_port}
                        for p in c.ports if p.container_port]
    res: Dict[str, dict] = {}
    if c.resources.cpu:
        res.setdefault("requests", {})["cpu"] = str(c.resources.cpu)
    if c.resources.memory_gb:
        res.setdefault("requests", {})["memory"] = f"{c.resources.memory_gb}Gi"
    if c.resources.tpu_chips:
        # google.com/tpu must appear in requests AND limits (extended
        # resource); GKE rejects TPU pods without the limit.
        res.setdefault("requests", {})[TPU_RESOURCE] = str(c.resources.tpu_chips)
        res.setdefault("limits", {})[TPU_RESOURCE] = str(c.resources.tpu_chips)
    if res:
        out["resources"] = res
    return out


def to_k8s_pod(pod: Pod, node: Optional[Node] = None) -> dict:
    """Translate a plane Pod (post-scheduling) to a K8s Pod manifest.

    The plane scheduler already chose the host (``pod.node_name``) — that
    decision is pinned via the hostname selector so the kube-scheduler
    cannot undo slice-aware gang placement. The slice-binding annotation
    additionally folds into REQUIRED nodeAffinity on the node-pool label
    (in-place-scheduling parity: ``sync/node_binding.go:276``)."""
    tpl = pod.template
    tpu_pod = any(c.resources.tpu_chips for c in tpl.containers)

    labels = dict(tpl.labels)
    labels[LABEL_MANAGED_BY] = MANAGED_BY
    annotations = dict(tpl.annotations)
    annotations[ANN_PLANE_UID] = pod.metadata.uid

    spec: dict = {
        "containers": [_container_to_k8s(c) for c in tpl.containers],
        "restartPolicy": ("Never" if annotations.get(
            f"{C.DOMAIN}/run-to-completion") == "true" else "Always"),
    }
    if tpl.init_containers:
        spec["initContainers"] = [_container_to_k8s(c)
                                  for c in tpl.init_containers]
    if tpu_pod:
        spec["hostNetwork"] = True
        spec["dnsPolicy"] = "ClusterFirstWithHostNet"

    node_selector = dict(tpl.node_selector)
    if pod.node_name:
        node_selector[LABEL_HOSTNAME] = pod.node_name
    if node is not None and node.tpu.accelerator:
        node_selector.setdefault(LABEL_GKE_TPU_ACCEL, node.tpu.accelerator)
        if node.tpu.slice_topology:
            node_selector.setdefault(LABEL_GKE_TPU_TOPOLOGY,
                                     node.tpu.slice_topology)
    if node_selector:
        spec["nodeSelector"] = node_selector

    # Affinity: plane NodeAffinityTerms + slice binding.
    required_terms = []
    preferred = []
    for t in pod.affinity:
        expr = {"key": t.key, "operator": t.operator}
        if t.values:
            expr["values"] = list(t.values)
        if t.required:
            required_terms.append(expr)
        else:
            preferred.append({"weight": t.weight, "preference":
                              {"matchExpressions": [expr]}})
    slice_pin = pod.metadata.annotations.get(C.ANN_SLICE_BINDING, "")
    if slice_pin:
        required_terms.append({"key": LABEL_GKE_NODEPOOL, "operator": "In",
                               "values": [slice_pin]})
    affinity: dict = {}
    if required_terms:
        # K8s semantics: expressions inside ONE term AND together
        # (Required folding, node_binding.go:409).
        affinity["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [{"matchExpressions": required_terms}]}
    if preferred:
        affinity["preferredDuringSchedulingIgnoredDuringExecution"] = preferred
    if affinity:
        spec["affinity"] = {"nodeAffinity": affinity}

    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "labels": labels,
            "annotations": annotations,
        },
        "spec": spec,
    }


def desired_images(pod: Pod) -> Dict[str, str]:
    return {c.name: c.image for c in pod.template.containers}


def reflect_status(kpod: dict, pod_fallback_revision: str = "") -> dict:
    """Extract the plane-relevant status fields from a K8s Pod JSON.

    Returns a dict consumed by the backend's status mutator: phase, ready,
    pod_ip, node, start_time (epoch), container restarts, running images,
    and reason."""
    st = kpod.get("status", {})
    conds = {c.get("type"): c.get("status")
             for c in st.get("conditions", [])}
    restarts: Dict[str, int] = {}
    images: Dict[str, str] = {}
    for cs in st.get("containerStatuses", []):
        restarts[cs.get("name", "")] = int(cs.get("restartCount", 0))
        if cs.get("state", {}).get("running") is not None:
            images[cs.get("name", "")] = cs.get("image", "")
    start = st.get("startTime") or 0.0
    if isinstance(start, str):
        # Real apiservers serialize RFC3339 ("2026-07-29T12:00:00Z");
        # the fake uses epoch floats.
        import datetime
        try:
            start = datetime.datetime.fromisoformat(
                start.replace("Z", "+00:00")).timestamp()
        except ValueError:
            start = 0.0
    return {
        "phase": st.get("phase", "Pending"),
        "reason": st.get("reason", ""),
        "ready": conds.get("Ready") == "True",
        "pod_ip": st.get("podIP", ""),
        "node_name": kpod.get("spec", {}).get("nodeName", ""),
        "start_time": float(start) if isinstance(start, (int, float)) else 0.0,
        "container_restarts": restarts,
        "running_images": images,
        "deleting": kpod.get("metadata", {}).get("deletionTimestamp") is not None,
    }


def node_from_k8s(knode: dict) -> Node:
    """Build a plane Node from a K8s Node (TPU labels → TpuNodeInfo). The
    node-pool label is the slice id; worker index comes from the plane's
    own label when present (set by admin tooling) else 0."""
    meta = knode.get("metadata", {})
    labels = meta.get("labels", {}) or {}
    status = knode.get("status", {})
    capacity = status.get("capacity", {}) or {}
    addresses = status.get("addresses", []) or []
    addr = next((a.get("address") for a in addresses
                 if a.get("type") == "InternalIP"), "127.0.0.1")
    conds = {c.get("type"): c.get("status")
             for c in status.get("conditions", [])}
    node = Node()
    node.metadata.name = meta.get("name", "")
    node.metadata.namespace = "default"
    node.labels = dict(labels)
    node.ready = conds.get("Ready", "True") == "True"
    node.address = addr
    node.capacity_pods = int(capacity.get("pods", 64))
    # Disruption lifecycle (GKE maintenance events / spot preemption):
    # spec.unschedulable is the cordon bit; a Preempted or
    # MaintenancePending condition maps to the plane's disruption field,
    # with the advance-notice deadline carried as a node annotation.
    node.unschedulable = bool(knode.get("spec", {}).get("unschedulable"))
    annotations = meta.get("annotations", {}) or {}
    if conds.get(COND_PREEMPTED) == "True":
        node.disruption = C.DISRUPT_PREEMPTED
    elif conds.get(COND_MAINTENANCE) == "True":
        node.disruption = C.DISRUPT_MAINTENANCE
        try:
            node.disruption_deadline = float(
                annotations.get(ANN_MAINT_DEADLINE, 0.0))
        except (TypeError, ValueError):
            node.disruption_deadline = 0.0
    node.tpu = TpuNodeInfo(
        accelerator=labels.get(LABEL_GKE_TPU_ACCEL, ""),
        slice_id=labels.get(LABEL_GKE_NODEPOOL, ""),
        slice_topology=labels.get(LABEL_GKE_TPU_TOPOLOGY, ""),
        worker_index=int(labels.get(LABEL_WORKER_INDEX, 0)),
        chips=int(capacity.get(TPU_RESOURCE, 0)),
        mesh_coords=labels.get(f"{C.DOMAIN}/mesh-coords", ""),
    )
    return node
