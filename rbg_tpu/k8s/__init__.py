"""Kubernetes pod backend — realize plane Pods as real Kubernetes Pods.

The reference is entirely a K8s operator (``cmd/rbgs/main.go:126``,
``pkg/reconciler/pod_reconciler.go:64-390``): its pods ARE Kubernetes pods.
This plane keeps its own store and scheduler (slice-aware gang placement the
kube-scheduler cannot do), and this package is the third backend behind the
kubelet seam (``rbg_tpu/runtime/plane.py``): it mirrors plane Pods to a real
(or in-repo fake) Kubernetes API server as GKE-TPU-shaped Pods and reflects
their live status back into the plane store.

Pieces:

* ``client``  — minimal K8s REST client (urllib/http.client, token auth,
  resourceVersion-aware CRUD + JSON-lines watch).
* ``translate`` — plane Pod ↔ K8s Pod JSON (``google.com/tpu`` resources,
  ``cloud.google.com/gke-tpu-*`` selectors, slice-binding → nodeAffinity),
  plane Node ↔ K8s Node (TPU labels).
* ``backend`` — ``K8sPodBackend``: the kubelet-seam implementation.
* ``fake_apiserver`` — in-repo fake of the K8s REST semantics (CRUD +
  resourceVersion conflicts + watch + a kwok-style node agent) for tests:
  no cluster exists in this environment (SURVEY.md §4 envtest analog).
"""

from rbg_tpu.k8s.backend import K8sPodBackend
from rbg_tpu.k8s.client import ApiError, Conflict, KubeClient, NotFound
from rbg_tpu.k8s.fake_apiserver import FakeK8sApiServer

__all__ = ["K8sPodBackend", "KubeClient", "FakeK8sApiServer",
           "ApiError", "Conflict", "NotFound"]
