"""Windowed signals over the metrics Registry: a bounded ring-buffer
sampler exposing ``rate`` / ``delta`` / ``mean_gauge`` / ``mean_observed``
over sliding windows.

The Registry holds cumulative counters and point-in-time gauges — enough
for a scrape pipeline, useless for a control decision ("is goodput
falling over the last 60 s?"). This module is the consumable in-process
answer: a daemon sampler snapshots the registry every ``interval_s``
seconds into a bounded ring (``retention_s`` worth of samples, oldest
evicted), and the query API turns any cataloged series into a windowed
number. The SLO plane (obs/slo.py), ``rbg-tpu top``, and the future
autoscaler / agg↔disagg switcher (ROADMAP) all read THIS api — none of
them re-derive windows from raw scrapes.

Conventions:

* windows are the standard ``WINDOWS_S`` (10 s / 60 s / 300 s) unless a
  caller passes its own;
* ``rbg_*`` names are validated against the obs/names.py catalog — the
  lint discipline of PRs 4-6 carries into the query layer (a typo'd name
  returns an error at the call site, not a silent 0.0);
* counter queries sum over every series matching the given label SUBSET
  (``rate(names.SLO_GOODPUT_TOTAL, 60, role="decode")`` sums all decode
  series whatever their other labels);
* counter resets (a restarted plane mid-window) follow the Prometheus
  convention: a decrease reads as "reset to zero, then grew to the new
  value", so the increase never goes negative.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_lock

WINDOWS_S = (10.0, 60.0, 300.0)
DEFAULT_INTERVAL_S = 2.0
# Default retention covers the largest standard window plus one interval
# of slack so the boundary sample is still in the ring.
DEFAULT_RETENTION_S = 330.0


def _check_name(name: str) -> None:
    if name.startswith("rbg_"):
        from rbg_tpu.obs import names as _names
        if name not in _names.ALL_NAMES:
            raise ValueError(
                f"metric {name!r} is not cataloged in rbg_tpu/obs/names.py "
                f"— windowed queries only serve registered names")


def _match(key: Tuple[str, tuple], name: str, want: frozenset) -> bool:
    return key[0] == name and want.issubset(set(key[1]))


class TimeSeriesSampler:
    """Periodic registry snapshots + windowed queries.

    ``start()`` spawns the daemon sampling thread (idempotent);
    ``stop()`` wakes and joins it. ``sample_now(now=...)`` takes one
    snapshot synchronously — tests inject their own clock through it, so
    window math is deterministic without sleeping."""

    def __init__(self, registry=None, interval_s: float = DEFAULT_INTERVAL_S,
                 retention_s: float = DEFAULT_RETENTION_S):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if retention_s < interval_s:
            raise ValueError("retention_s must be >= interval_s")
        self.registry = registry if registry is not None else REGISTRY
        self.interval_s = float(interval_s)
        self.retention_s = float(retention_s)
        maxlen = max(2, int(self.retention_s / self.interval_s) + 1)
        # Ring of (t, counters, gauges, hists) snapshot tuples.
        self._samples = collections.deque(maxlen=maxlen)  # guarded_by[obs.timeseries]
        self._lock = named_lock("obs.timeseries")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self) -> "TimeSeriesSampler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="timeseries-sampler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)
        self._thread = None

    def _loop(self) -> None:
        # Seed one sample immediately so the first window query after
        # start() has a baseline, then sample on the interval.
        self.sample_now()
        while not self._stop.wait(self.interval_s):
            self.sample_now()

    # -- sampling --

    def sample_now(self, now: Optional[float] = None) -> None:
        """Take one snapshot. ``now`` overrides the monotonic timestamp
        (tests). Snapshot + timestamp + append happen under ONE critical
        section: two concurrent callers (the daemon tick racing a
        drill's closing sample) could otherwise append an older registry
        copy after a newer one, which the reset-aware delta would read
        as a counter restart and inflate the window by the cumulative
        total. The registry lock nests inside ours and is a plain leaf
        lock — no ordering hazard."""
        with self._lock:
            counters, gauges, hists = self.registry.snapshot_values()
            t = time.monotonic() if now is None else float(now)
            if self._samples and t < self._samples[-1][0]:
                t = self._samples[-1][0]   # append order IS time order
            self._samples.append((t, counters, gauges, hists))

    def last_sample_age_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the newest sample (None when nothing was sampled
        yet). The staleness input for control loops: a dead scrape thread
        must read as 'no signal', never as 'rate fell to zero'."""
        with self._lock:
            if not self._samples:
                return None
            last = self._samples[-1][0]
        anchor = time.monotonic() if now is None else float(now)
        return max(0.0, anchor - last)

    def stats(self) -> dict:
        with self._lock:
            n = len(self._samples)
            span = (self._samples[-1][0] - self._samples[0][0]) if n else 0.0
        return {"samples": n, "interval_s": self.interval_s,
                "retention_s": self.retention_s, "span_s": round(span, 3),
                "running": bool(self._thread and self._thread.is_alive())}

    # -- queries --

    def _window(self, window_s: float, now: Optional[float]) -> List[tuple]:
        """Samples covering the window, newest-anchored: everything at or
        after ``cutoff`` plus the last sample BEFORE it (the baseline a
        full-window delta measures against)."""
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return []
        anchor = samples[-1][0] if now is None else float(now)
        cutoff = anchor - window_s
        # Interior = strictly inside the window; baseline = the newest
        # sample AT or before the cutoff (a sample exactly on the
        # boundary already carries the pre-window totals — adding an
        # older one would silently widen the window).
        inside = [s for s in samples if cutoff < s[0] <= anchor]
        before = [s for s in samples if s[0] <= cutoff]
        if before:
            inside.insert(0, before[-1])
        return inside

    @staticmethod
    def _increase(win: List[tuple], name: str, labels: dict, field: int,
                  hist_part: Optional[int] = None):
        """Summed monotonic increase across matching series over an
        already-materialized window (None when fewer than two samples
        cover it). ``field`` picks the snapshot store; ``hist_part``
        picks sum/count out of a histogram pair. Callers pass the SAME
        ``win`` for related queries (Δsum and Δcount of one histogram) so
        a concurrent sampler tick cannot skew them apart."""
        if len(win) < 2:
            return None, None
        want = frozenset(labels.items())
        total = 0.0
        prev: Dict[tuple, float] = {}
        first = True
        for sample in win:
            store = sample[field]
            seen = set()
            for key, v in store.items():
                if not _match(key, name, want):
                    continue
                if hist_part is not None:
                    v = v[hist_part]
                seen.add(key)
                if key in prev:
                    d = v - prev[key]
                    # Reset: the counter restarted from zero and grew to
                    # v — count v, never a negative delta.
                    total += v if d < 0 else d
                elif not first:
                    # Series born mid-window: it went 0 -> v inside it.
                    total += v
                prev[key] = v
            # A series that vanished (registry reset) restarts from its
            # next appearance — drop its baseline so the reappearance is
            # counted as a fresh birth, not diffed against stale state.
            for key in [k for k in prev if k not in seen]:
                del prev[key]
            first = False
        elapsed = win[-1][0] - win[0][0]
        return total, elapsed

    def delta(self, name: str, window_s: float, now: Optional[float] = None,
              **labels) -> Optional[float]:
        """Counter increase over the window (reset-aware), summed across
        every series matching the label subset. None until two samples
        cover the window."""
        _check_name(name)
        total, _ = self._increase(self._window(window_s, now), name,
                                  labels, field=1)
        return total

    def rate(self, name: str, window_s: float, now: Optional[float] = None,
             **labels) -> Optional[float]:
        """Per-second counter rate over the window (delta / observed
        sample span)."""
        _check_name(name)
        total, elapsed = self._increase(self._window(window_s, now), name,
                                        labels, field=1)
        if total is None or not elapsed or elapsed <= 0:
            return None
        return total / elapsed

    def mean_gauge(self, name: str, window_s: float,
                   now: Optional[float] = None, **labels) -> Optional[float]:
        """Mean of the gauge over the window's samples; matching series
        are summed per sample first (e.g. queue depth across services).
        None when no sample in the window carries the series."""
        _check_name(name)
        want = frozenset(labels.items())
        vals = []
        for sample in self._window(window_s, now):
            matched = [v for key, v in sample[2].items()
                       if _match(key, name, want)]
            if matched:
                vals.append(sum(matched))
        if not vals:
            return None
        return sum(vals) / len(vals)

    def mean_observed(self, name: str, window_s: float,
                      now: Optional[float] = None,
                      **labels) -> Optional[float]:
        """Mean VALUE observed into a histogram over the window:
        Δsum / Δcount across matching series (reset-aware). The windowed
        complement of ``Registry.quantile`` — mean occupancy, mean queue
        depth at submission, mean TTFT. One window materialization feeds
        both deltas, so a sampler tick between them cannot mismatch the
        numerator's sample set against the denominator's."""
        _check_name(name)
        win = self._window(window_s, now)
        dsum, _ = self._increase(win, name, labels, field=3, hist_part=0)
        dcount, _ = self._increase(win, name, labels, field=3, hist_part=1)
        if dsum is None or not dcount:
            return None
        return dsum / dcount


# ---- process-wide default sampler ------------------------------------------

_DEFAULT: Optional[TimeSeriesSampler] = None
_DEFAULT_LOCK = threading.Lock()


def get_sampler() -> TimeSeriesSampler:
    """The process-wide sampler over the global REGISTRY (created on
    first use, NOT started — call :func:`ensure_started` or drive it with
    ``sample_now()``). Knobs: ``RBG_TS_INTERVAL_S`` / ``RBG_TS_RETENTION_S``."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            interval = float(os.environ.get("RBG_TS_INTERVAL_S")
                             or DEFAULT_INTERVAL_S)
            retention = float(os.environ.get("RBG_TS_RETENTION_S")
                              or DEFAULT_RETENTION_S)
            _DEFAULT = TimeSeriesSampler(interval_s=interval,
                                         retention_s=retention)
        return _DEFAULT


def ensure_started() -> TimeSeriesSampler:
    """Start (idempotently) and return the process-wide sampler — what
    serving processes and drills call once at boot."""
    return get_sampler().start()
