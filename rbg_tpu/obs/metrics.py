"""Metrics: counters + histograms with Prometheus text exposition.

Reference analog: controller-runtime's Prometheus metrics server
(``cmd/rbgs/main.go:270-314``) — reconcile totals/errors/durations per
controller, workqueue depths. Exposed through the admin API (op "metrics")
in text exposition format, so a scrape sidecar can forward them.
"""

from __future__ import annotations

import os
import threading
from collections import defaultdict
from typing import Dict, Optional, Tuple

_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
            1.0, 2.5, 5.0, 10.0)


class Registry:
    """NOTE: this lock stays a plain threading.Lock, not a locktrace
    named_lock — inversion reporting itself increments a counter, and a
    traced metrics lock would re-enter here mid-report."""

    def __init__(self, strict: Optional[bool] = None):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, tuple], float] = defaultdict(float)
        self._hist: Dict[Tuple[str, tuple], list] = {}
        self._gauges: Dict[Tuple[str, tuple], float] = {}
        # Strict mode (RBG_METRICS_STRICT=1): the runtime complement of
        # the metric-name-registry lint rule — an rbg_* name emitted under
        # the wrong kind, or missing from obs/names.py, raises at the call
        # site instead of silently minting a new series.
        if strict is None:
            v = (os.environ.get("RBG_METRICS_STRICT") or "").strip().lower()
            # Same off-values as RBG_LOCKTRACE: "0"/"false"/"off" disable.
            strict = bool(v) and v not in ("0", "false", "off")
        self._strict = strict

    def _check(self, name: str, kind: str):
        if not (self._strict and name.startswith("rbg_")):
            return
        from rbg_tpu.obs import names as _names
        catalog = {"counter": _names.COUNTERS, "gauge": _names.GAUGES,
                   "histogram": _names.HISTOGRAMS}[kind]
        if name not in catalog:
            raise ValueError(
                f"metric {name!r} is not cataloged as a {kind} in "
                f"rbg_tpu/obs/names.py (RBG_METRICS_STRICT is set)")

    def inc(self, name: str, value: float = 1.0, **labels):
        self._check(name, "counter")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value

    def set_gauge(self, name: str, value: float, **labels):
        """Last-write-wins gauge (queue depth, drain state, ...)."""
        self._check(name, "gauge")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def gauge(self, name: str, **labels) -> Optional[float]:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key)

    def counter(self, name: str, **labels) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def observe(self, name: str, value: float, exemplar: Optional[str] = None,
                **labels):
        """``exemplar``: a trace_id to remember for the bucket this value
        lands in (the SLOWEST value per bucket wins) — a bad quantile then
        links to a concrete trace waterfall via the ``traces`` op."""
        self._check(name, "histogram")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                # buckets, sum, count, observed max, per-bucket exemplar
                h = [[0] * (len(_BUCKETS) + 1), 0.0, 0, 0.0,
                     [None] * (len(_BUCKETS) + 1)]
                self._hist[key] = h
            for i, b in enumerate(_BUCKETS):
                if value <= b:
                    h[0][i] += 1
                    break
            else:
                i = len(_BUCKETS)
                h[0][-1] += 1
            h[1] += value
            h[2] += 1
            h[3] = max(h[3], value)
            if exemplar is not None:
                ex = h[4][i]
                if ex is None or value >= ex[0]:
                    h[4][i] = (value, exemplar)

    def quantile(self, name: str, q: float, **labels) -> Optional[float]:
        """Approximate quantile from histogram buckets (upper bound). A
        quantile landing in the overflow bucket reports the OBSERVED max
        instead of +Inf — "all samples overflowed" has a finite answer."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hist.get(key)
            if h is None or h[2] == 0:
                return None
            target = q * h[2]
            seen = 0
            for i, count in enumerate(h[0]):
                seen += count
                if seen >= target:
                    return _BUCKETS[i] if i < len(_BUCKETS) else h[3]
            return h[3]

    def hist_stats(self, name: str, **labels) -> Optional[dict]:
        """``{count, sum, max}`` for one histogram series (None when the
        series doesn't exist) — the cheap aggregate the fleet report
        pairs with ``quantile`` percentile points."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                return None
            return {"count": h[2], "sum": h[1], "max": h[3]}

    def exemplars(self, name: str, **labels) -> Dict[str, dict]:
        """{le: {"value", "trace_id"}} for one histogram series — the
        slowest traced observation per bucket."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                return {}
            out = {}
            for i, ex in enumerate(h[4]):
                if ex is None:
                    continue
                le = str(_BUCKETS[i]) if i < len(_BUCKETS) else "+Inf"
                out[le] = {"value": ex[0], "trace_id": ex[1]}
            return out

    def exemplars_snapshot(self) -> list:
        """Every bucket exemplar across every histogram series, flat —
        what the ``traces`` op returns so an operator can walk quantile →
        trace_id → waterfall."""
        with self._lock:
            out = []
            for (name, labels), h in sorted(self._hist.items()):
                for i, ex in enumerate(h[4]):
                    if ex is None:
                        continue
                    out.append({
                        "metric": name, "labels": dict(labels),
                        "le": (str(_BUCKETS[i]) if i < len(_BUCKETS)
                               else "+Inf"),
                        "value": round(ex[0], 6), "trace_id": ex[1]})
            return out

    def render(self, exemplars: bool = False) -> str:
        """Prometheus text exposition, with ``# HELP``/``# TYPE`` metadata
        per family (help text from the obs/names.py catalog; the type is
        known from which store the family lives in). ``exemplars=True``
        appends OpenMetrics-style ``# {trace_id="..."} v`` exemplars to
        bucket lines — off by default so plain Prometheus text parsers
        stay happy."""
        from rbg_tpu.obs import names as _names
        lines = []
        seen = set()

        def meta(name: str, kind: str):
            if name in seen:
                return
            seen.add(name)
            help_text = _names.HELP.get(name)
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")

        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                meta(name, "counter")
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                meta(name, "gauge")
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), h in sorted(self._hist.items()):
                buckets, total, count = h[0], h[1], h[2]
                meta(name, "histogram")
                cum = 0
                for i, b in enumerate(_BUCKETS):
                    cum += buckets[i]
                    line = f"{name}_bucket{_fmt(labels, le=b)} {cum}"
                    lines.append(self._exemplar_suffix(line, h[4][i])
                                 if exemplars else line)
                cum += buckets[-1]
                line = f'{name}_bucket{_fmt(labels, le="+Inf")} {cum}'
                lines.append(self._exemplar_suffix(line, h[4][-1])
                             if exemplars else line)
                lines.append(f"{name}_sum{_fmt(labels)} {total}")
                lines.append(f"{name}_count{_fmt(labels)} {count}")
        return "\n".join(lines) + "\n"

    @staticmethod
    def _exemplar_suffix(line: str, ex) -> str:
        if ex is None:
            return line
        return f'{line} # {{trace_id="{ex[1]}"}} {ex[0]}'

    def snapshot_values(self):
        """Point-in-time copies for the time-series sampler
        (obs/timeseries.py): ``(counters, gauges, hists)`` keyed by
        ``(name, sorted_label_tuple)``; histogram series are reduced to
        ``(sum, count)`` pairs so windowed means cost two counter deltas."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (h[1], h[2]) for k, h in self._hist.items()}
        return counters, gauges, hists

    def label_values(self, name: str, label: str) -> set:
        """Distinct values one label takes across every live series of a
        family — what a staleness sweep diffs against its live set."""
        out = set()
        with self._lock:
            for store in (self._counters, self._gauges, self._hist):
                for key in store:
                    if key[0] == name:
                        v = dict(key[1]).get(label)
                        if v is not None:
                            out.add(v)
        return out

    def remove_series(self, name: str, **labels) -> int:
        """Drop every series of ``name`` whose labels include ``labels``
        (label-scoped reset; no labels = the whole family). Evicting a
        backend must take its per-backend gauges out of the exposition —
        a dead address otherwise renders forever. Returns the number of
        series removed."""
        want = set(labels.items())
        removed = 0
        with self._lock:
            for store in (self._counters, self._gauges, self._hist):
                dead = [k for k in store
                        if k[0] == name and want.issubset(set(k[1]))]
                for k in dead:
                    del store[k]
                removed += len(dead)
        return removed

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._hist.clear()
            self._gauges.clear()


def _esc(v) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    line-feed must be escaped or the series line is malformed (some
    scrapers reject the whole exposition)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(labels: tuple, **extra) -> str:
    items = list(labels) + sorted(extra.items())
    if not items:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in items)
    return "{" + inner + "}"


REGISTRY = Registry()
