"""Metrics: counters + histograms with Prometheus text exposition.

Reference analog: controller-runtime's Prometheus metrics server
(``cmd/rbgs/main.go:270-314``) — reconcile totals/errors/durations per
controller, workqueue depths. Exposed through the admin API (op "metrics")
in text exposition format, so a scrape sidecar can forward them.
"""

from __future__ import annotations

import os
import threading
from collections import defaultdict
from typing import Dict, Optional, Tuple

_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
            1.0, 2.5, 5.0, 10.0)


class Registry:
    """NOTE: this lock stays a plain threading.Lock, not a locktrace
    named_lock — inversion reporting itself increments a counter, and a
    traced metrics lock would re-enter here mid-report."""

    def __init__(self, strict: Optional[bool] = None):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, tuple], float] = defaultdict(float)
        self._hist: Dict[Tuple[str, tuple], list] = {}
        self._gauges: Dict[Tuple[str, tuple], float] = {}
        # Strict mode (RBG_METRICS_STRICT=1): the runtime complement of
        # the metric-name-registry lint rule — an rbg_* name emitted under
        # the wrong kind, or missing from obs/names.py, raises at the call
        # site instead of silently minting a new series.
        if strict is None:
            v = (os.environ.get("RBG_METRICS_STRICT") or "").strip().lower()
            # Same off-values as RBG_LOCKTRACE: "0"/"false"/"off" disable.
            strict = bool(v) and v not in ("0", "false", "off")
        self._strict = strict

    def _check(self, name: str, kind: str):
        if not (self._strict and name.startswith("rbg_")):
            return
        from rbg_tpu.obs import names as _names
        catalog = {"counter": _names.COUNTERS, "gauge": _names.GAUGES,
                   "histogram": _names.HISTOGRAMS}[kind]
        if name not in catalog:
            raise ValueError(
                f"metric {name!r} is not cataloged as a {kind} in "
                f"rbg_tpu/obs/names.py (RBG_METRICS_STRICT is set)")

    def inc(self, name: str, value: float = 1.0, **labels):
        self._check(name, "counter")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value

    def set_gauge(self, name: str, value: float, **labels):
        """Last-write-wins gauge (queue depth, drain state, ...)."""
        self._check(name, "gauge")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def gauge(self, name: str, **labels) -> Optional[float]:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key)

    def counter(self, name: str, **labels) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def observe(self, name: str, value: float, **labels):
        self._check(name, "histogram")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                h = [[0] * (len(_BUCKETS) + 1), 0.0, 0]  # buckets, sum, count
                self._hist[key] = h
            for i, b in enumerate(_BUCKETS):
                if value <= b:
                    h[0][i] += 1
                    break
            else:
                h[0][-1] += 1
            h[1] += value
            h[2] += 1

    def quantile(self, name: str, q: float, **labels) -> Optional[float]:
        """Approximate quantile from histogram buckets (upper bound)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hist.get(key)
            if h is None or h[2] == 0:
                return None
            target = q * h[2]
            seen = 0
            for i, count in enumerate(h[0]):
                seen += count
                if seen >= target:
                    return _BUCKETS[i] if i < len(_BUCKETS) else float("inf")
            return float("inf")

    def render(self) -> str:
        """Prometheus text exposition."""
        lines = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                lines.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), (buckets, total, count) in sorted(self._hist.items()):
                cum = 0
                for i, b in enumerate(_BUCKETS):
                    cum += buckets[i]
                    lines.append(f"{name}_bucket{_fmt(labels, le=b)} {cum}")
                cum += buckets[-1]
                lines.append(f'{name}_bucket{_fmt(labels, le="+Inf")} {cum}')
                lines.append(f"{name}_sum{_fmt(labels)} {total}")
                lines.append(f"{name}_count{_fmt(labels)} {count}")
        return "\n".join(lines) + "\n"

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._hist.clear()
            self._gauges.clear()


def _fmt(labels: tuple, **extra) -> str:
    items = list(labels) + sorted(extra.items())
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + inner + "}"


REGISTRY = Registry()
