"""Canonical catalog of ``rbg_*`` metric names.

One module owns every metric name the project emits. Call sites import the
constant instead of retyping the string — the ``metric-name-registry``
lint rule (``rbg_tpu/analysis/rules/metricnames.py``) flags any ``rbg_*``
literal passed to a ``REGISTRY`` method that is not cataloged here, any
counter whose name is missing the ``_total`` suffix, and any name
registered under two different kinds (e.g. the same name used as both a
counter and a gauge).

Naming contract (Prometheus conventions):

* counters end in ``_total``;
* histograms of durations end in ``_seconds``;
* gauges are bare nouns (``..._depth``, ``..._draining``).

Keep this module to plain ``NAME = "literal"`` assignments grouped by
kind — the lint rule parses it statically.
"""

from __future__ import annotations

# ---- counters (monotonic, name must end in _total) ----

RECONCILE_TOTAL = "rbg_reconcile_total"
SERVING_SHED_TOTAL = "rbg_serving_shed_total"
SERVING_DEADLINE_EXCEEDED_TOTAL = "rbg_serving_deadline_exceeded_total"
SERVING_DRAINS_TOTAL = "rbg_serving_drains_total"
SERVING_DRAIN_REFUSALS_TOTAL = "rbg_serving_drain_refusals_total"
DISRUPTION_NOTICES_TOTAL = "rbg_disruption_notices_total"
DISRUPTION_PREEMPTIONS_TOTAL = "rbg_disruption_preemptions_total"
DISRUPTION_GANG_KILLS_TOTAL = "rbg_disruption_gang_kills_total"
DISRUPTION_MIGRATIONS_COMPLETED_TOTAL = (
    "rbg_disruption_migrations_completed_total")
DISRUPTION_MIGRATIONS_MISSED_DEADLINE_TOTAL = (
    "rbg_disruption_migrations_missed_deadline_total")
DISRUPTION_SLICES_RELEASED_TOTAL = "rbg_disruption_slices_released_total"
DISRUPTION_SPARES_CONSUMED_TOTAL = "rbg_disruption_spares_consumed_total"
LOCKTRACE_INVERSIONS_TOTAL = "rbg_locktrace_inversions_total"
RACE_CHECKED_TOTAL = "rbg_race_checked_total"
RACE_VIOLATIONS_TOTAL = "rbg_race_violations_total"

# ---- gauges (last-write-wins) ----

SERVING_DRAINING = "rbg_serving_draining"
DISRUPTION_SPARE_POOL_DEPTH = "rbg_disruption_spare_pool_depth"
RACE_GUARDED_CLASSES = "rbg_race_guarded_classes"

# ---- histograms ----

RECONCILE_DURATION_SECONDS = "rbg_reconcile_duration_seconds"
SERVING_QUEUE_DEPTH = "rbg_serving_queue_depth"

# ---- catalog sets (consumed by the lint rule and strict-mode registry) ----

COUNTERS = frozenset({
    RECONCILE_TOTAL,
    SERVING_SHED_TOTAL,
    SERVING_DEADLINE_EXCEEDED_TOTAL,
    SERVING_DRAINS_TOTAL,
    SERVING_DRAIN_REFUSALS_TOTAL,
    DISRUPTION_NOTICES_TOTAL,
    DISRUPTION_PREEMPTIONS_TOTAL,
    DISRUPTION_GANG_KILLS_TOTAL,
    DISRUPTION_MIGRATIONS_COMPLETED_TOTAL,
    DISRUPTION_MIGRATIONS_MISSED_DEADLINE_TOTAL,
    DISRUPTION_SLICES_RELEASED_TOTAL,
    DISRUPTION_SPARES_CONSUMED_TOTAL,
    LOCKTRACE_INVERSIONS_TOTAL,
    RACE_CHECKED_TOTAL,
    RACE_VIOLATIONS_TOTAL,
})

GAUGES = frozenset({
    SERVING_DRAINING,
    DISRUPTION_SPARE_POOL_DEPTH,
    RACE_GUARDED_CLASSES,
})

HISTOGRAMS = frozenset({
    RECONCILE_DURATION_SECONDS,
    SERVING_QUEUE_DEPTH,
})

ALL_NAMES = COUNTERS | GAUGES | HISTOGRAMS
