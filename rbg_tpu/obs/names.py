"""Canonical catalog of ``rbg_*`` metric names.

One module owns every metric name the project emits. Call sites import the
constant instead of retyping the string — the ``metric-name-registry``
lint rule (``rbg_tpu/analysis/rules/metricnames.py``) flags any ``rbg_*``
literal passed to a ``REGISTRY`` method that is not cataloged here, any
counter whose name is missing the ``_total`` suffix, and any name
registered under two different kinds (e.g. the same name used as both a
counter and a gauge).

Naming contract (Prometheus conventions):

* counters end in ``_total``;
* histograms of durations end in ``_seconds``;
* gauges are bare nouns (``..._depth``, ``..._draining``).

Keep this module to plain ``NAME = "literal"`` assignments grouped by
kind — the lint rule parses it statically.
"""

from __future__ import annotations

# ---- counters (monotonic, name must end in _total) ----

RECONCILE_TOTAL = "rbg_reconcile_total"
SERVING_SHED_TOTAL = "rbg_serving_shed_total"
SERVING_DEADLINE_EXCEEDED_TOTAL = "rbg_serving_deadline_exceeded_total"
SERVING_DRAINS_TOTAL = "rbg_serving_drains_total"
SERVING_DRAIN_REFUSALS_TOTAL = "rbg_serving_drain_refusals_total"
DISRUPTION_NOTICES_TOTAL = "rbg_disruption_notices_total"
DISRUPTION_PREEMPTIONS_TOTAL = "rbg_disruption_preemptions_total"
DISRUPTION_GANG_KILLS_TOTAL = "rbg_disruption_gang_kills_total"
DISRUPTION_MIGRATIONS_COMPLETED_TOTAL = (
    "rbg_disruption_migrations_completed_total")
DISRUPTION_MIGRATIONS_MISSED_DEADLINE_TOTAL = (
    "rbg_disruption_migrations_missed_deadline_total")
DISRUPTION_SLICES_RELEASED_TOTAL = "rbg_disruption_slices_released_total"
DISRUPTION_SPARES_CONSUMED_TOTAL = "rbg_disruption_spares_consumed_total"
LOCKTRACE_INVERSIONS_TOTAL = "rbg_locktrace_inversions_total"
RACE_CHECKED_TOTAL = "rbg_race_checked_total"
RACE_VIOLATIONS_TOTAL = "rbg_race_violations_total"
JIT_COMPILES_TOTAL = "rbg_jit_compiles_total"
JIT_UNWARMED_COMPILES_TOTAL = "rbg_jit_unwarmed_compiles_total"
JIT_HOST_SYNCS_TOTAL = "rbg_jit_host_syncs_total"
WIRE_CONTRACT_VIOLATIONS_TOTAL = "rbg_wire_contract_violations_total"
TRACE_TRACES_TOTAL = "rbg_trace_traces_total"
TRACE_SPANS_DROPPED_TOTAL = "rbg_trace_spans_dropped_total"
SERVING_REQUESTS_FINISHED_TOTAL = "rbg_serving_requests_finished_total"
SERVING_TOKENS_TOTAL = "rbg_serving_tokens_total"
SLO_JUDGED_TOTAL = "rbg_slo_judged_total"
SLO_TTFT_MET_TOTAL = "rbg_slo_ttft_met_total"
SLO_TPOT_MET_TOTAL = "rbg_slo_tpot_met_total"
SLO_GOODPUT_TOTAL = "rbg_slo_goodput_total"
AUTOSCALE_DECISIONS_TOTAL = "rbg_autoscale_decisions_total"
AUTOSCALE_CLAMPED_TOTAL = "rbg_autoscale_clamped_total"
AUTOSCALE_COOLDOWN_SUPPRESSED_TOTAL = (
    "rbg_autoscale_cooldown_suppressed_total")
AUTOSCALE_STALE_HOLDS_TOTAL = "rbg_autoscale_stale_holds_total"
AUTOSCALE_CONFLICTS_TOTAL = "rbg_autoscale_conflicts_total"
AUTOSCALE_SPARE_GRANTS_TOTAL = "rbg_autoscale_spare_grants_total"
KVT_CHUNKS_TOTAL = "rbg_kvtransfer_chunks_total"
KVT_BYTES_TOTAL = "rbg_kvtransfer_bytes_total"
KVT_STREAMS_TOTAL = "rbg_kvtransfer_streams_total"
KVT_LAYER_ADMIT_TOTAL = "rbg_kvtransfer_layer_admit_total"
KVT_DIR_LOOKUPS_TOTAL = "rbg_kvtransfer_dir_lookups_total"
KVT_DIR_INVALIDATIONS_TOTAL = "rbg_kvtransfer_dir_invalidations_total"
WORKQUEUE_ADDS_TOTAL = "rbg_workqueue_adds_total"
RECONCILE_REQUEUES_TOTAL = "rbg_reconcile_requeues_total"
RECONCILE_DEDUPED_TOTAL = "rbg_reconcile_deduped_total"
RESYNC_BACKSTOP_ENQUEUED_TOTAL = "rbg_resync_backstop_enqueued_total"
RESYNC_BACKSTOP_SKIPPED_TOTAL = "rbg_resync_backstop_skipped_total"
SCHED_SHARD_SCANS_TOTAL = "rbg_sched_shard_scans_total"
SCHED_SHARD_SKIPS_TOTAL = "rbg_sched_shard_skips_total"
WATCH_REPLAYS_TOTAL = "rbg_watch_replays_total"
WATCH_EVENTS_TOTAL = "rbg_watch_events_total"
WATCH_DELIVERIES_TOTAL = "rbg_watch_deliveries_total"
SCHED_BINDS_TOTAL = "rbg_sched_binds_total"
EVENTS_RECORDED_TOTAL = "rbg_events_recorded_total"
EVENTS_DEDUPED_TOTAL = "rbg_events_deduped_total"
EVENTS_EVICTED_TOTAL = "rbg_events_evicted_total"
TOPOLOGY_FLIPS_TOTAL = "rbg_topology_flips_total"
TOPOLOGY_HOLDS_TOTAL = "rbg_topology_holds_total"
TOPOLOGY_COST_GATED_TOTAL = "rbg_topology_cost_gated_total"
TOPOLOGY_CONFLICTS_TOTAL = "rbg_topology_conflicts_total"
KVC_TIER_HITS_TOTAL = "rbg_kvcache_tier_hits_total"
KVC_TIER_MISSES_TOTAL = "rbg_kvcache_tier_misses_total"
KVC_TIER_SPILLED_PAGES_TOTAL = "rbg_kvcache_tier_spilled_pages_total"
KVC_TIER_PROMOTED_PAGES_TOTAL = "rbg_kvcache_tier_promoted_pages_total"
KVC_TIER_EVICTED_PAGES_TOTAL = "rbg_kvcache_tier_evicted_pages_total"
KVT_DIR_REPLICATIONS_TOTAL = "rbg_kvtransfer_dir_replications_total"
ROUTER_INGRESS_TOKENS_TOTAL = "rbg_router_ingress_tokens_total"
SERVING_EARLY_REJECTS_TOTAL = "rbg_serving_early_rejects_total"
ROUTER_RING_ROUTES_TOTAL = "rbg_router_ring_routes_total"
ROUTER_RING_RESHARDS_TOTAL = "rbg_router_ring_reshards_total"
ROUTER_PEER_EVENTS_TOTAL = "rbg_router_peer_events_total"
PLANE_LEADER_TRANSITIONS_TOTAL = "rbg_plane_leader_transitions_total"
PLANE_FENCED_WRITES_TOTAL = "rbg_plane_fenced_writes_total"
PLANE_STANDBY_TAIL_EVENTS_TOTAL = "rbg_plane_standby_tail_events_total"
KVT_DIR_BREAKER_OPEN_TOTAL = "rbg_kvtransfer_dir_breaker_open_total"
KVT_CHUNKS_DUPLICATE_TOTAL = "rbg_kvtransfer_chunks_duplicate_total"
KVT_CHUNKS_REORDERED_TOTAL = "rbg_kvtransfer_chunks_reordered_total"
KVT_INTEGRITY_FAILURES_TOTAL = "rbg_kvtransfer_integrity_failures_total"
CHAOS_FAULTS_INJECTED_TOTAL = "rbg_chaos_faults_injected_total"
PLANE_SELF_DEMOTIONS_TOTAL = "rbg_plane_self_demotions_total"

# ---- gauges (last-write-wins) ----

SERVING_DRAINING = "rbg_serving_draining"
DISRUPTION_SPARE_POOL_DEPTH = "rbg_disruption_spare_pool_depth"
RACE_GUARDED_CLASSES = "rbg_race_guarded_classes"
SLO_TTFT_ATTAINMENT = "rbg_slo_ttft_attainment"
SLO_TPOT_ATTAINMENT = "rbg_slo_tpot_attainment"
SLO_GOODPUT_RPS = "rbg_slo_goodput_rps"
ROUTER_BACKEND_OUTSTANDING = "rbg_router_backend_outstanding"
ROUTER_BACKEND_DRAINING = "rbg_router_backend_draining"
AUTOSCALE_TARGET_REPLICAS = "rbg_autoscale_target_replicas"
AUTOSCALE_ACTUAL_REPLICAS = "rbg_autoscale_actual_replicas"
KVT_LINK_RATE = "rbg_kvtransfer_link_bytes_per_s"
KVT_DIR_ENTRIES = "rbg_kvtransfer_dir_entries"
WORKQUEUE_DEPTH = "rbg_workqueue_depth"
WORKQUEUE_RETRIES_PENDING = "rbg_workqueue_retries_pending"
EVENTS_OBJECTS = "rbg_events_objects"
TOPOLOGY_POSTURE = "rbg_topology_posture"
KVC_TIER_PAGES = "rbg_kvcache_tier_pages"
KVC_TIER_BYTES = "rbg_kvcache_tier_bytes"
ROUTER_RING_MEMBERS = "rbg_router_ring_members"
PLANE_LEADER_STATE = "rbg_plane_leader_state"
PLANE_LEADER_EPOCH = "rbg_plane_leader_epoch"
SERVING_RETRY_BUDGET_TOKENS = "rbg_serving_retry_budget_tokens"
DEGRADED_MODE = "rbg_degraded_mode"

# ---- histograms ----

RECONCILE_DURATION_SECONDS = "rbg_reconcile_duration_seconds"
SERVING_QUEUE_DEPTH = "rbg_serving_queue_depth"
SERVING_REQUEST_DURATION_SECONDS = "rbg_serving_request_duration_seconds"
SERVING_BATCH_OCCUPANCY = "rbg_serving_batch_occupancy"
SERVING_JOIN_LATENCY_SECONDS = "rbg_serving_join_latency_seconds"
SLO_TTFT_SECONDS = "rbg_slo_ttft_seconds"
SLO_TPOT_SECONDS = "rbg_slo_tpot_seconds"
PD_LOCK_HOLD_SECONDS = "rbg_pd_lock_hold_seconds"
KVT_ADMIT_LEAD_SECONDS = "rbg_kvtransfer_admit_lead_seconds"
KVT_LAYER_ADMIT_LEAD_SECONDS = "rbg_kvtransfer_layer_admit_lead_seconds"
KVT_LAYER_ADMIT_COVERAGE_LAYERS = (
    "rbg_kvtransfer_layer_admit_coverage_layers")
WORKQUEUE_QUEUE_AGE_SECONDS = "rbg_workqueue_queue_age_seconds"
WATCH_DISPATCH_SECONDS = "rbg_watch_dispatch_seconds"
SCHED_FEASIBILITY_SCAN_SECONDS = "rbg_sched_feasibility_scan_seconds"
TOPOLOGY_SWITCH_DURATION_SECONDS = "rbg_topology_switch_duration_seconds"
KVC_TIER_SPILL_SECONDS = "rbg_kvcache_tier_spill_seconds"
KVC_TIER_PROMOTE_SECONDS = "rbg_kvcache_tier_promote_seconds"
SERVING_PREDICTED_TTFT_SECONDS = "rbg_serving_predicted_ttft_seconds"

# ---- catalog sets (consumed by the lint rule and strict-mode registry) ----

COUNTERS = frozenset({
    RECONCILE_TOTAL,
    SERVING_SHED_TOTAL,
    SERVING_DEADLINE_EXCEEDED_TOTAL,
    SERVING_DRAINS_TOTAL,
    SERVING_DRAIN_REFUSALS_TOTAL,
    DISRUPTION_NOTICES_TOTAL,
    DISRUPTION_PREEMPTIONS_TOTAL,
    DISRUPTION_GANG_KILLS_TOTAL,
    DISRUPTION_MIGRATIONS_COMPLETED_TOTAL,
    DISRUPTION_MIGRATIONS_MISSED_DEADLINE_TOTAL,
    DISRUPTION_SLICES_RELEASED_TOTAL,
    DISRUPTION_SPARES_CONSUMED_TOTAL,
    LOCKTRACE_INVERSIONS_TOTAL,
    RACE_CHECKED_TOTAL,
    RACE_VIOLATIONS_TOTAL,
    JIT_COMPILES_TOTAL,
    JIT_UNWARMED_COMPILES_TOTAL,
    JIT_HOST_SYNCS_TOTAL,
    WIRE_CONTRACT_VIOLATIONS_TOTAL,
    TRACE_TRACES_TOTAL,
    TRACE_SPANS_DROPPED_TOTAL,
    SERVING_REQUESTS_FINISHED_TOTAL,
    SERVING_TOKENS_TOTAL,
    SLO_JUDGED_TOTAL,
    SLO_TTFT_MET_TOTAL,
    SLO_TPOT_MET_TOTAL,
    SLO_GOODPUT_TOTAL,
    AUTOSCALE_DECISIONS_TOTAL,
    AUTOSCALE_CLAMPED_TOTAL,
    AUTOSCALE_COOLDOWN_SUPPRESSED_TOTAL,
    AUTOSCALE_STALE_HOLDS_TOTAL,
    AUTOSCALE_CONFLICTS_TOTAL,
    AUTOSCALE_SPARE_GRANTS_TOTAL,
    KVT_CHUNKS_TOTAL,
    KVT_BYTES_TOTAL,
    KVT_STREAMS_TOTAL,
    KVT_LAYER_ADMIT_TOTAL,
    KVT_DIR_LOOKUPS_TOTAL,
    KVT_DIR_INVALIDATIONS_TOTAL,
    WORKQUEUE_ADDS_TOTAL,
    RECONCILE_REQUEUES_TOTAL,
    RECONCILE_DEDUPED_TOTAL,
    RESYNC_BACKSTOP_ENQUEUED_TOTAL,
    RESYNC_BACKSTOP_SKIPPED_TOTAL,
    SCHED_SHARD_SCANS_TOTAL,
    SCHED_SHARD_SKIPS_TOTAL,
    WATCH_REPLAYS_TOTAL,
    WATCH_EVENTS_TOTAL,
    WATCH_DELIVERIES_TOTAL,
    SCHED_BINDS_TOTAL,
    EVENTS_RECORDED_TOTAL,
    EVENTS_DEDUPED_TOTAL,
    EVENTS_EVICTED_TOTAL,
    TOPOLOGY_FLIPS_TOTAL,
    TOPOLOGY_HOLDS_TOTAL,
    TOPOLOGY_COST_GATED_TOTAL,
    TOPOLOGY_CONFLICTS_TOTAL,
    KVC_TIER_HITS_TOTAL,
    KVC_TIER_MISSES_TOTAL,
    KVC_TIER_SPILLED_PAGES_TOTAL,
    KVC_TIER_PROMOTED_PAGES_TOTAL,
    KVC_TIER_EVICTED_PAGES_TOTAL,
    KVT_DIR_REPLICATIONS_TOTAL,
    ROUTER_INGRESS_TOKENS_TOTAL,
    SERVING_EARLY_REJECTS_TOTAL,
    ROUTER_RING_ROUTES_TOTAL,
    ROUTER_RING_RESHARDS_TOTAL,
    ROUTER_PEER_EVENTS_TOTAL,
    PLANE_LEADER_TRANSITIONS_TOTAL,
    PLANE_FENCED_WRITES_TOTAL,
    PLANE_STANDBY_TAIL_EVENTS_TOTAL,
    KVT_DIR_BREAKER_OPEN_TOTAL,
    KVT_CHUNKS_DUPLICATE_TOTAL,
    KVT_CHUNKS_REORDERED_TOTAL,
    KVT_INTEGRITY_FAILURES_TOTAL,
    CHAOS_FAULTS_INJECTED_TOTAL,
    PLANE_SELF_DEMOTIONS_TOTAL,
})

GAUGES = frozenset({
    SERVING_DRAINING,
    DISRUPTION_SPARE_POOL_DEPTH,
    RACE_GUARDED_CLASSES,
    SLO_TTFT_ATTAINMENT,
    SLO_TPOT_ATTAINMENT,
    SLO_GOODPUT_RPS,
    ROUTER_BACKEND_OUTSTANDING,
    ROUTER_BACKEND_DRAINING,
    AUTOSCALE_TARGET_REPLICAS,
    AUTOSCALE_ACTUAL_REPLICAS,
    KVT_LINK_RATE,
    KVT_DIR_ENTRIES,
    WORKQUEUE_DEPTH,
    WORKQUEUE_RETRIES_PENDING,
    EVENTS_OBJECTS,
    TOPOLOGY_POSTURE,
    KVC_TIER_PAGES,
    KVC_TIER_BYTES,
    ROUTER_RING_MEMBERS,
    PLANE_LEADER_STATE,
    PLANE_LEADER_EPOCH,
    SERVING_RETRY_BUDGET_TOKENS,
    DEGRADED_MODE,
})

HISTOGRAMS = frozenset({
    RECONCILE_DURATION_SECONDS,
    SERVING_QUEUE_DEPTH,
    SERVING_REQUEST_DURATION_SECONDS,
    SERVING_BATCH_OCCUPANCY,
    SERVING_JOIN_LATENCY_SECONDS,
    SLO_TTFT_SECONDS,
    SLO_TPOT_SECONDS,
    PD_LOCK_HOLD_SECONDS,
    KVT_ADMIT_LEAD_SECONDS,
    KVT_LAYER_ADMIT_LEAD_SECONDS,
    KVT_LAYER_ADMIT_COVERAGE_LAYERS,
    WORKQUEUE_QUEUE_AGE_SECONDS,
    WATCH_DISPATCH_SECONDS,
    SCHED_FEASIBILITY_SCAN_SECONDS,
    TOPOLOGY_SWITCH_DURATION_SECONDS,
    KVC_TIER_SPILL_SECONDS,
    KVC_TIER_PROMOTE_SECONDS,
    SERVING_PREDICTED_TTFT_SECONDS,
})

ALL_NAMES = COUNTERS | GAUGES | HISTOGRAMS

# ---- exposition help text (render() emits it as # HELP) ----

HELP = {
    RECONCILE_TOTAL: "Reconcile passes per controller and result",
    SERVING_SHED_TOTAL: "Requests shed by admission control",
    SERVING_DEADLINE_EXCEEDED_TOTAL:
        "Requests dropped or aborted past their deadline, per stage",
    SERVING_DRAINS_TOTAL: "SIGTERM drains started",
    SERVING_DRAIN_REFUSALS_TOTAL: "Data ops refused while draining",
    DISRUPTION_NOTICES_TOTAL: "Advance maintenance notices observed",
    DISRUPTION_PREEMPTIONS_TOTAL: "No-notice slice preemptions observed",
    DISRUPTION_GANG_KILLS_TOTAL: "Whole-gang kills after partial slice loss",
    DISRUPTION_MIGRATIONS_COMPLETED_TOTAL:
        "Maintenance migrations completed before their deadline",
    DISRUPTION_MIGRATIONS_MISSED_DEADLINE_TOTAL:
        "Maintenance migrations that missed their deadline",
    DISRUPTION_SLICES_RELEASED_TOTAL: "Slices released to maintenance",
    DISRUPTION_SPARES_CONSUMED_TOTAL: "Warm spare slices granted",
    LOCKTRACE_INVERSIONS_TOTAL: "Lock acquisition-order inversions observed",
    RACE_CHECKED_TOTAL: "Guarded-field accesses checked by racetrace",
    RACE_VIOLATIONS_TOTAL: "Guarded-field accesses without the owning lock",
    JIT_COMPILES_TOTAL: "XLA compiles recorded while jitwatch is armed",
    JIT_UNWARMED_COMPILES_TOTAL:
        "Cataloged programs compiled after warmup_complete(), per program",
    JIT_HOST_SYNCS_TOTAL:
        "Device-to-host syncs observed by the jitwatch probe",
    WIRE_CONTRACT_VIOLATIONS_TOTAL:
        "Wire frames violating the api/ops.py contract, per op and kind",
    TRACE_TRACES_TOTAL: "Traces finalized into the trace sink, per result",
    TRACE_SPANS_DROPPED_TOTAL:
        "Spans dropped by the per-trace span bound",
    SERVING_DRAINING: "1 while this process is draining",
    DISRUPTION_SPARE_POOL_DEPTH: "Reserved warm spare slices per topology",
    RACE_GUARDED_CLASSES: "Classes instrumented by the race detector",
    RECONCILE_DURATION_SECONDS: "Reconcile latency per controller",
    SERVING_QUEUE_DEPTH: "Service queue depth observed at submission",
    SERVING_REQUEST_DURATION_SECONDS:
        "End-to-end request latency inside the serving loop",
    SERVING_BATCH_OCCUPANCY:
        "Running-batch fill fraction (running / max_batch) observed per "
        "engine step",
    SERVING_JOIN_LATENCY_SECONDS:
        "Wait between entering the engine queue and joining the running "
        "batch",
    SERVING_REQUESTS_FINISHED_TOTAL:
        "Requests that finished generation (the SLO-judged population)",
    SERVING_TOKENS_TOTAL: "Output tokens produced by finished requests",
    SLO_JUDGED_TOTAL: "Finished requests judged against the SLO targets",
    SLO_TTFT_MET_TOTAL: "Judged requests whose TTFT met its target",
    SLO_TPOT_MET_TOTAL: "Judged requests whose TPOT met its target",
    SLO_GOODPUT_TOTAL:
        "Judged requests meeting BOTH the TTFT and TPOT targets",
    SLO_TTFT_ATTAINMENT:
        "Sliding-window fraction of judged requests meeting the TTFT "
        "target",
    SLO_TPOT_ATTAINMENT:
        "Sliding-window fraction of judged requests meeting the TPOT "
        "target",
    SLO_GOODPUT_RPS:
        "Sliding-window requests/s meeting both SLO targets",
    ROUTER_BACKEND_OUTSTANDING:
        "In-flight requests the router holds against one backend",
    ROUTER_BACKEND_DRAINING: "1 while the router sees this backend draining",
    AUTOSCALE_DECISIONS_TOTAL:
        "Autoscaler actuations per role and direction (up/down)",
    AUTOSCALE_CLAMPED_TOTAL:
        "Autoscaler targets clamped by min/max or the coordination skew "
        "bound",
    AUTOSCALE_COOLDOWN_SUPPRESSED_TOTAL:
        "Autoscaler decisions suppressed by the post-actuation cooldown",
    AUTOSCALE_STALE_HOLDS_TOTAL:
        "Autoscaler evaluations held because the signal plane was stale",
    AUTOSCALE_CONFLICTS_TOTAL:
        "Autoscaler back-offs after a foreign writer touched the adapter",
    AUTOSCALE_SPARE_GRANTS_TOTAL:
        "Warm spare slices granted to autoscaler-created instances",
    AUTOSCALE_TARGET_REPLICAS:
        "Replica target the autoscaler last wrote, per role",
    AUTOSCALE_ACTUAL_REPLICAS: "Ready replicas observed per role",
    SLO_TTFT_SECONDS: "Time to first token of judged requests",
    SLO_TPOT_SECONDS:
        "Per-output-token latency after the first token, per judged "
        "request",
    KVT_CHUNKS_TOTAL: "KV transfer chunks moved, per direction",
    KVT_BYTES_TOTAL: "KV transfer payload bytes moved, per direction "
                     "and transport",
    KVT_STREAMS_TOTAL: "KV chunk streams completed, per outcome",
    KVT_DIR_LOOKUPS_TOTAL:
        "Cluster prefix-directory lookups, per result (hit/miss)",
    KVT_DIR_INVALIDATIONS_TOTAL:
        "Prefix-directory entries invalidated, per reason",
    KVT_LINK_RATE:
        "Measured KV link throughput from real transfers, per transport",
    KVT_DIR_ENTRIES: "Live prefix-directory entries",
    PD_LOCK_HOLD_SECONDS:
        "Time a PD critical-section lock was held, per lock",
    KVT_ADMIT_LEAD_SECONDS:
        "How long before its stream finished a streamed decode row was "
        "admitted (coverage-complete vs stream-close lead)",
    KVT_LAYER_ADMIT_TOTAL:
        "Layer-sliced decode admissions dispatched (first decode step "
        "started before full KV coverage)",
    KVT_LAYER_ADMIT_LEAD_SECONDS:
        "How long before FULL coverage a layer-sliced admission could "
        "start (layer-watermark-ready vs coverage-complete lead)",
    KVT_LAYER_ADMIT_COVERAGE_LAYERS:
        "Leading fully-covered layers at the moment of a layer-sliced "
        "admission",
    WORKQUEUE_ADDS_TOTAL:
        "Keys enqueued into a controller workqueue, per controller",
    RECONCILE_REQUEUES_TOTAL:
        "Reconcile keys re-queued, per controller and reason "
        "(error backoff vs requeue_after revisit)",
    RECONCILE_DEDUPED_TOTAL:
        "Dequeued keys skipped because every pending trigger version was "
        "already covered by a completed reconcile, per controller "
        "(coalesced stale events, status-only self-writes, backstop "
        "sweeps of unchanged objects)",
    RESYNC_BACKSTOP_ENQUEUED_TOTAL:
        "Keys the periodic drift-backstop resync enqueued, per controller "
        "(a healthy event path keeps this near zero useful work — the "
        "dedup counter absorbs unchanged keys)",
    RESYNC_BACKSTOP_SKIPPED_TOTAL:
        "Keys the drift-backstop resync skipped because the event path "
        "already reconciled them since the last backstop tick, per "
        "controller",
    SCHED_SHARD_SCANS_TOTAL:
        "Topology shards (slices) whose hosts the feasibility scan "
        "actually visited",
    SCHED_SHARD_SKIPS_TOTAL:
        "Topology shards pruned by the free-capacity index before any "
        "host was visited (shard cannot fit the gang)",
    WATCH_REPLAYS_TOTAL:
        "Store watch events replayed to a subscriber resuming from a "
        "resourceVersion watermark, per kind",
    WATCH_EVENTS_TOTAL: "Store watch events published, per kind and type",
    WATCH_DELIVERIES_TOTAL:
        "Watch handler invocations (event fan-out), per kind",
    SCHED_BINDS_TOTAL: "Pods bound to nodes by the scheduler",
    EVENTS_RECORDED_TOTAL:
        "Control-plane events recorded, per type (dedup bumps included)",
    EVENTS_DEDUPED_TOTAL:
        "Event records collapsed into an existing record's count",
    EVENTS_EVICTED_TOTAL:
        "Event occurrences evicted by the per-object/per-plane bounds",
    WORKQUEUE_DEPTH: "Ready keys in a controller workqueue, per controller",
    WORKQUEUE_RETRIES_PENDING:
        "Keys currently carrying failure backoff, per controller",
    EVENTS_OBJECTS: "Objects with live event history in the recorder",
    WORKQUEUE_QUEUE_AGE_SECONDS:
        "Enqueue-to-dequeue wait of workqueue keys (intentional "
        "add_after delay excluded), per controller",
    WATCH_DISPATCH_SECONDS:
        "Time to deliver one store event to every subscriber, per kind",
    SCHED_FEASIBILITY_SCAN_SECONDS:
        "Scheduler feasibility scan (placement plan computation) duration",
    TOPOLOGY_POSTURE:
        "PD shape of a role group: 0 unified, 1 disaggregated, 0.5 while "
        "a flip is in progress",
    TOPOLOGY_FLIPS_TOTAL:
        "Completed topology flips, per group and target shape",
    TOPOLOGY_HOLDS_TOTAL:
        "Topology evaluations that held the current shape, per reason "
        "(stale / deadband / stabilizing / cooldown / no_ratio / "
        "low_sample)",
    TOPOLOGY_COST_GATED_TOTAL:
        "Topology flips vetoed because the estimated KV move cost over "
        "measured link rates exceeded the gate",
    TOPOLOGY_CONFLICTS_TOTAL:
        "Topology flips backed off because another actuator's adapter "
        "write was in flight",
    TOPOLOGY_SWITCH_DURATION_SECONDS:
        "Wall time of a completed topology flip (warm start to old-shape "
        "drained), per target shape",
    KVC_TIER_HITS_TOTAL:
        "Prefix-cache hits per tier (device = radix, host = spill tier)",
    KVC_TIER_MISSES_TOTAL:
        "Prefix lookups that missed every cache tier",
    KVC_TIER_SPILLED_PAGES_TOTAL:
        "KV pages spilled device-tier → host-tier on device eviction",
    KVC_TIER_PROMOTED_PAGES_TOTAL:
        "KV pages promoted host-tier → device-tier on a host hit",
    KVC_TIER_EVICTED_PAGES_TOTAL:
        "Cached KV pages evicted from a tier's bounded store, per tier "
        "(host = byte-budget LRU-by-hotness eviction)",
    KVC_TIER_PAGES: "Cached KV pages resident, per tier",
    KVC_TIER_BYTES: "Cached KV bytes resident, per tier",
    KVC_TIER_SPILL_SECONDS:
        "Device→host page spill latency (device readback + trie insert)",
    KVC_TIER_PROMOTE_SECONDS:
        "Host→device page promotion latency (trie take + device scatter)",
    KVT_DIR_REPLICATIONS_TOTAL:
        "Hot single-holder prefixes the router deliberately routed to a "
        "non-holder so a second replica computes and registers them",
    ROUTER_INGRESS_TOKENS_TOTAL:
        "Tokens observed at router ingress, per kind (prefill = prompt "
        "tokens dispatched, decode = output tokens delivered) — the "
        "production prefill:decode ratio signal for the topology policy",
    SERVING_EARLY_REJECTS_TOTAL:
        "Requests shed at ingress because predicted TTFT (queue wait + "
        "prefill net of the prefix hit this request would get) exceeded "
        "the SLO gate — before any prefill compute was spent",
    SERVING_PREDICTED_TTFT_SECONDS:
        "Predicted TTFT computed by the admission gate for each "
        "submission it evaluated",
    ROUTER_RING_MEMBERS:
        "Live (non-draining) router replicas on the consistent-hash ring",
    ROUTER_RING_ROUTES_TOTAL:
        "Tier routing decisions, per result (affinity = hash owner taken, "
        "fallback = bounded-load spill to the next replica, rescue = "
        "owner dead/draining, range absorbed by a peer)",
    ROUTER_RING_RESHARDS_TOTAL:
        "Ring membership changes (a router joined, drained, or died — "
        "its hash range moved to peers)",
    ROUTER_PEER_EVENTS_TOTAL:
        "Router-to-router feed events delivered, per type (backend "
        "health/draining transitions, measured link rates, ingress "
        "token counters)",
    PLANE_LEADER_STATE:
        "1 while this control-plane candidate holds the leader lease, "
        "0 on standby, per plane",
    PLANE_LEADER_EPOCH:
        "Fencing epoch of the current leader lease (monotone; bumps on "
        "every takeover)",
    PLANE_LEADER_TRANSITIONS_TOTAL:
        "Leadership acquisitions, per plane (a takeover after leader "
        "death or graceful handover)",
    PLANE_FENCED_WRITES_TOTAL:
        "Store writes refused because they carried a stale lease epoch "
        "(a deposed leader's in-flight actuation), per lease",
    PLANE_STANDBY_TAIL_EVENTS_TOTAL:
        "Store watch events tailed by a standby plane keeping its resume "
        "watermark warm, per plane",
    KVT_DIR_BREAKER_OPEN_TOTAL:
        "Prefix-directory client circuit-breaker opens (decorrelated-"
        "jitter exponential window, not a fixed wall-clock hold)",
    SERVING_RETRY_BUDGET_TOKENS:
        "Retry-budget tokens currently available in THIS router process "
        "(fleet-wide effective budget is N x per-replica after router "
        "scale-out)",
    KVT_CHUNKS_DUPLICATE_TOTAL:
        "KV chunk frames delivered more than once (already fully "
        "written when they arrived) — a degrading link retransmits "
        "before it truncates",
    KVT_CHUNKS_REORDERED_TOTAL:
        "KV chunk frames that arrived out of send order (a lower seq "
        "after a higher one, duplicates excluded) — reorder depth is a "
        "link-health leading indicator",
    KVT_INTEGRITY_FAILURES_TOTAL:
        "KV payloads whose bytes failed their end-to-end checksum, per "
        "surface (chunk = wire frame at decode commit, pool = cached "
        "page at match/extend, peer_fetch = directory-advertised "
        "remote page) — every one was refused, never served",
    CHAOS_FAULTS_INJECTED_TOTAL:
        "Faults the deterministic chaos plane injected, per kind "
        "(partition / corrupt / skew / brownout) — drill-only; nonzero "
        "in production means a chaos schedule leaked into prod config",
    PLANE_SELF_DEMOTIONS_TOTAL:
        "Leaders that stepped down proactively because lease renewal "
        "stopped landing (partition from the lease store) before their "
        "TTL could expire under a contending standby, per plane",
    DEGRADED_MODE:
        "1 while a graceful-degradation ladder rung is engaged, per "
        "ladder (directory = local-affinity-only routing, peer_feed = "
        "stale tier members excluded from the ring, lease = leader "
        "self-demoted on renewal failure) — 0 after heal",
}

# ---- span names (obs/trace.py) ----
#
# Same contract as the metric catalog: every span name the tracer emits is
# declared here once, the ``span-name-registry`` lint rule flags literals
# that are not, and ``RBG_TRACE_STRICT=1`` adds the same check at span
# creation time. Naming contract: lowercase dotted ``component.phase``.

SPAN_HTTP_REQUEST = "http.request"
SPAN_ROUTER_REQUEST = "router.request"
SPAN_ROUTER_ATTEMPT = "router.attempt"
SPAN_ENGINE_OP = "engine.op"
SPAN_SERVICE_QUEUE_WAIT = "service.queue_wait"
SPAN_SERVICE_SCAN = "service.scan"
SPAN_PD_PREFILL = "pd.prefill"
SPAN_PD_KV_HANDOFF = "pd.kv_handoff"
SPAN_KVT_PUSH = "kvtransfer.push"
SPAN_KVT_COMMIT = "kvtransfer.commit"
SPAN_PD_LAYER_SLICED_STEP = "pd.layer_sliced_step"
SPAN_STRESS_REQUEST = "stress.request"
SPAN_CTRL_EVENT = "controller.event"
SPAN_CTRL_RECONCILE = "controller.reconcile"
SPAN_TOPOLOGY_FLIP = "topology.flip"
SPAN_TOPOLOGY_WARM = "topology.warm"
SPAN_TOPOLOGY_CUTOVER = "topology.cutover"
SPAN_TOPOLOGY_DRAIN = "topology.drain"
SPAN_PLANE_TAKEOVER = "plane.takeover"
SPAN_ROUTER_RESHARD = "router.reshard"

# ---- jitted program catalog (jitwatch sentry + warmers) ----
#
# Same contract as the metric catalog: every named hot-path XLA program
# the engine builds is declared here once — the builders stamp the inner
# callable's __name__ with the constant (XLA's sym_name is "jit_" + that
# name), the warmers pre-compile them, and the jitwatch sentry gates on
# exactly this set after warmup_complete(). A program missing here is
# invisible to the recompile gate; a warmer that silently stops covering
# a cataloged variant is a drill failure. Naming contract: ``rbg_<area>``.

PROGRAM_RAGGED_FWD = "rbg_ragged_fwd"          # Engine._get_ragged_fn
PROGRAM_PAGED_FWD = "rbg_paged_fwd"            # Engine._get_fwd
PROGRAM_FUSED_DECODE = "rbg_fused_decode"      # Engine._get_decode_fn
PROGRAM_SPEC_VERIFY = "rbg_spec_verify"        # Engine._get_spec_fn
PROGRAM_SAMPLER = "rbg_sampler"                # Engine._get_sampler
PROGRAM_PD_WINDOW = "rbg_pd_window"            # DecodeWorker._get_window_fn
PROGRAM_PD_HEAD = "rbg_pd_head"                # DecodeWorker._get_head_fn
PROGRAM_EMBED_POOLED = "rbg_embed_pooled"      # service._embed_batch
PROGRAM_KVTIER_PROMOTE = "rbg_kvtier_promote"  # kvtier._promote_scatter

PROGRAMS = frozenset({
    PROGRAM_RAGGED_FWD,
    PROGRAM_PAGED_FWD,
    PROGRAM_FUSED_DECODE,
    PROGRAM_SPEC_VERIFY,
    PROGRAM_SAMPLER,
    PROGRAM_PD_WINDOW,
    PROGRAM_PD_HEAD,
    PROGRAM_EMBED_POOLED,
    PROGRAM_KVTIER_PROMOTE,
})

# ---- bucketing-helper catalog (bucket-discipline lint rule) ----
#
# The registered shape launderers: a raw shape (len(...), .shape) may
# reach a jitted program's cache key or a program-getter argument only
# through one of these (each carries a ``# bucket_fn`` annotation at its
# definition). The static rule audits the annotation set against this
# catalog so a helper added in code but not cataloged (or vice versa) is
# itself a finding.

BUCKET_FNS = frozenset({
    "_pow2_bucket",      # engine/kvtier.py — pow2 page counts
    "_bucket",           # engine/engine.py — decode_buckets table
    "_token_bucket",     # engine/engine.py — packed-token pow2 (>= 8)
    "_chunk_bucket",     # engine/service.py — chunk-multiple pow2
})

SPANS = frozenset({
    SPAN_HTTP_REQUEST,
    SPAN_ROUTER_REQUEST,
    SPAN_ROUTER_ATTEMPT,
    SPAN_ENGINE_OP,
    SPAN_SERVICE_QUEUE_WAIT,
    SPAN_SERVICE_SCAN,
    SPAN_PD_PREFILL,
    SPAN_PD_KV_HANDOFF,
    SPAN_KVT_PUSH,
    SPAN_KVT_COMMIT,
    SPAN_PD_LAYER_SLICED_STEP,
    SPAN_STRESS_REQUEST,
    SPAN_CTRL_EVENT,
    SPAN_CTRL_RECONCILE,
    SPAN_TOPOLOGY_FLIP,
    SPAN_TOPOLOGY_WARM,
    SPAN_TOPOLOGY_CUTOVER,
    SPAN_TOPOLOGY_DRAIN,
    SPAN_PLANE_TAKEOVER,
    SPAN_ROUTER_RESHARD,
})
