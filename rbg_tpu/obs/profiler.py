"""All-thread sampling profiler (the pprof analog).

cProfile is per-thread — attached to an admin handler it would only see that
handler sleeping — so we SAMPLE every thread's stack via
``sys._current_frames``: a statistical CPU profile of the whole plane.
Used by the admin ``profile`` op (reference: ``cmd/rbgs/main.go:584-620``
pprof server) and captured into stress reports during load (reference:
``test/stress/pprof.go``).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import Counter
from typing import List, Optional


def _site(f) -> str:
    return f"{f.name} ({os.path.basename(f.filename)}:{f.lineno})"


def sample_profile(seconds: float = 2.0, interval: float = 0.01,
                   top_n: int = 30, stop_event: Optional[threading.Event] = None,
                   exclude_thread: Optional[int] = None,
                   folded_depth: int = 24, folded_top: int = 60) -> dict:
    """Sample all threads for ``seconds`` (or until ``stop_event``); return
    {"seconds", "samples", "top": [{"site", "samples"}], "folded": [...]}.

    ``top`` is the leaf-only hot-site table; ``folded`` carries the FULL
    stacks in flamegraph-folded form — ``root;caller;leaf N`` lines
    (oldest frame first, ``;``-joined, sample count last), directly
    consumable by flamegraph.pl / speedscope."""
    me = exclude_thread if exclude_thread is not None else threading.get_ident()
    counts: Counter = Counter()
    folded: Counter = Counter()
    t0 = time.monotonic()
    end = t0 + seconds
    samples = 0
    while time.monotonic() < end:
        if stop_event is not None and stop_event.is_set():
            break
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = traceback.extract_stack(frame)
            if stack:
                counts[_site(stack[-1])] += 1
                names = [_site(f) for f in stack]
                if len(names) > folded_depth:
                    # Root-anchored truncation: flamegraphs merge from the
                    # root, so deep stacks must keep their OLDEST frames
                    # (``limit=`` keeps the newest — same chain would render
                    # as many disconnected towers). Drop leaf-side frames
                    # and mark the elision.
                    names = names[:folded_depth - 1] + ["…truncated"]
                folded[";".join(names)] += 1
        samples += 1
        time.sleep(interval)
    return {
        "seconds": round(time.monotonic() - t0, 2),
        "samples": samples,
        "top": [{"site": site, "samples": n}
                for site, n in counts.most_common(top_n)],
        "folded": [f"{stack} {n}"
                   for stack, n in folded.most_common(folded_top)],
    }


class BackgroundProfiler:
    """Continuously sample while a load phase runs; ``stop()`` returns the
    profile. The stress harness wraps each phase in one of these."""

    def __init__(self, interval: float = 0.01, top_n: int = 25):
        self._interval = interval
        self._top_n = top_n
        self._stop = threading.Event()
        self._result: dict = {}
        self._thread: Optional[threading.Thread] = None

    def __enter__(self):
        def run():
            self._result = sample_profile(
                seconds=3600.0, interval=self._interval, top_n=self._top_n,
                stop_event=self._stop)
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="stress-profiler")
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return self._result

    @property
    def result(self) -> dict:
        return self._result
