"""Zero-dependency request tracing for the serving plane.

A request crosses http_frontend → router (retries/failover) → prefill →
KV handoff → decode → ``_BatchService`` queue/scan; the counters in
``obs/metrics.py`` say *that* p99 degraded, never *which hop* ate the
budget. This module is the per-request, per-hop timeline layer (the
Mooncake / "Taming the Chaos" trace-driven-analysis analog):

* :class:`Span` — trace_id / span_id / parent linkage, monotonic start +
  duration, structured attrs. Spans of one trace share a bounded
  ``_TraceState`` (``MAX_SPANS_PER_TRACE``; overflow is counted, never
  unbounded).
* ambient *current span* (thread-local stack, :func:`use_span` /
  :func:`current` / :func:`child`) so deep callees attach children
  without parameter plumbing;
* wire propagation: ``span.wire()`` rides request objects as
  ``obj["trace"] = {"trace_id", "parent_id", "sampled"}``;
  :func:`from_wire` continues an incoming context (joining the SAME
  in-process trace state when the hop shares the process — the stress
  drills see one rooted tree) and :func:`ingress_span` accepts a W3C
  ``traceparent`` header at the HTTP edge;
* a process-wide :class:`TraceSink` (``SINK``) holding two ring
  buffers — recent traces and slowest-N by root duration — pulled from a
  live plane via the admin / engine-server ``traces`` op;
* head-based sampling: the decision is made ONCE at ingress
  (``RBG_TRACE_SAMPLE``, default 1%) and rides the wire, so the hot
  decode loop is never perturbed for unsampled requests. Tracing off
  (``RBG_TRACE`` unset, the production default) means every entry point
  returns the falsy ``NULL_SPAN`` — same near-zero-overhead contract as
  locktrace.

``RBG_TRACE_STRICT=1`` is the runtime complement of the
``span-name-registry`` lint rule: a span name missing from the
``obs/names.py`` catalog raises at creation time.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Dict, List, Optional

from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import REGISTRY

MAX_SPANS_PER_TRACE = 128
MAX_ACTIVE_TRACES = 512


def _env_flag(var: str) -> bool:
    v = (os.environ.get(var) or "").strip().lower()
    return bool(v) and v not in ("0", "false", "off")


class _Config:
    def __init__(self):
        self.enabled = _env_flag("RBG_TRACE")
        try:
            self.sample = float(os.environ.get("RBG_TRACE_SAMPLE", "0.01"))
        except ValueError:
            self.sample = 0.01
        self.strict = _env_flag("RBG_TRACE_STRICT")


_CFG = _Config()


def configure(enabled: Optional[bool] = None,
              sample: Optional[float] = None,
              strict: Optional[bool] = None) -> None:
    """Programmatic arming (the stress harness / tests; production uses the
    RBG_TRACE* env vars). ``None`` leaves a knob unchanged."""
    if enabled is not None:
        _CFG.enabled = bool(enabled)
    if sample is not None:
        _CFG.sample = float(sample)
    if strict is not None:
        _CFG.strict = bool(strict)


def enabled() -> bool:
    return _CFG.enabled


def _check_name(name: str) -> None:
    if _CFG.strict and name not in names.SPANS:
        raise ValueError(
            f"span name {name!r} is not cataloged in rbg_tpu/obs/names.py "
            f"SPANS (RBG_TRACE_STRICT is set)")


def new_trace_id() -> str:
    return uuid.uuid4().hex            # 32 hex chars (traceparent-sized)


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]       # 16 hex chars


class _NullSpan:
    """Falsy no-op span: the disabled/unsampled path. Every method is a
    cheap constant so call sites stay unconditional."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    sampled = False

    def __bool__(self):
        return False

    def child(self, name, **attrs):
        return self

    def end(self, **attrs):
        return None

    def wire(self):
        return None

    # Same context-manager contract as Span so the two stay interchangeable
    # on the ``with span.child(...):`` form.
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


NULL_SPAN = _NullSpan()


class _TraceState:
    """Shared bookkeeping for the spans of one in-process trace. The lock
    is a plain (untraced) threading.Lock — spans are recorded from handler
    AND loop threads, and the tracer must never feed back into the
    detectors it helps debug."""

    __slots__ = ("trace_id", "root", "spans", "dropped", "finalized", "lock")

    def __init__(self, trace_id: str, root: "Span"):
        self.trace_id = trace_id
        self.root = root
        self.spans: List[Span] = [root]
        self.dropped = 0
        self.finalized = False
        self.lock = threading.Lock()

    def add(self, span: "Span") -> bool:
        with self.lock:
            if self.finalized or len(self.spans) >= MAX_SPANS_PER_TRACE:
                self.dropped += 1
                REGISTRY.inc(names.TRACE_SPANS_DROPPED_TOTAL)
                return False
            self.spans.append(span)
            return True


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0",
                 "duration_s", "attrs", "_state")

    sampled = True

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 state: Optional[_TraceState], attrs: Optional[dict] = None):
        _check_name(name)
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.t0 = time.monotonic()
        self.duration_s: Optional[float] = None
        self.attrs: Dict[str, object] = dict(attrs) if attrs else {}
        self._state = state

    def child(self, name: str, **attrs) -> "Span | _NullSpan":
        state = self._state
        if state is None:
            return NULL_SPAN
        sp = Span(name, self.trace_id, self.span_id, state, attrs)
        if not state.add(sp):
            return NULL_SPAN           # per-trace bound hit: drop, count
        return sp

    def end(self, **attrs) -> None:
        """Idempotent: the first end wins (error paths may double-end)."""
        if self.duration_s is not None:
            return
        self.duration_s = time.monotonic() - self.t0
        if attrs:
            self.attrs.update(attrs)
        state = self._state
        if state is not None and state.root is self:
            SINK._finalize(state)

    def wire(self) -> dict:
        """The context a downstream hop continues from (this span becomes
        the parent)."""
        return {"trace_id": self.trace_id, "parent_id": self.span_id,
                "sampled": True}

    # Context-manager form: ``with span.child(...) as sp:`` ends on exit.
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


# ---- ambient current-span context (per-thread) ----

_AMBIENT = threading.local()


def _stack() -> list:
    st = getattr(_AMBIENT, "stack", None)
    if st is None:
        st = _AMBIENT.stack = []
    return st


def current() -> "Span | _NullSpan":
    st = getattr(_AMBIENT, "stack", None)
    return st[-1] if st else NULL_SPAN


class use_span:
    """``with use_span(sp):`` makes ``sp`` the ambient current span for
    this thread. Pushing NULL_SPAN is legal (and cheap) so call sites
    never branch on sampling."""

    __slots__ = ("_span",)

    def __init__(self, span):
        self._span = span

    def __enter__(self):
        _stack().append(self._span)
        return self._span

    def __exit__(self, *exc):
        st = _stack()
        if st:
            st.pop()


def child(name: str, **attrs) -> "Span | _NullSpan":
    """Child of the ambient current span (NULL when nothing is ambient)."""
    return current().child(name, **attrs)


# ---- trace creation: ingress sampling + wire continuation ----


def start_trace(name: str, sample: Optional[bool] = None,
                **attrs) -> "Span | _NullSpan":
    """Root span for a NEW trace. The head-based sampling decision happens
    here, once; ``sample=True`` forces (the stress drills), ``None`` rolls
    the configured rate."""
    if not _CFG.enabled:
        return NULL_SPAN
    if sample is None:
        import random
        sample = random.random() < _CFG.sample
    if not sample:
        return NULL_SPAN
    tid = new_trace_id()
    root = Span(name, tid, None, None, attrs)
    root._state = SINK._open(tid, root)
    return root


def from_wire(ctx, name: str, **attrs) -> "Span | _NullSpan":
    """Continue an incoming wire context (``obj["trace"]``): the upstream
    hop already made the sampling decision. When the context names a trace
    whose state lives in THIS process (in-process multi-hop: router and
    service in one drill), the new span joins that state so the sink sees
    one rooted tree. No usable context ⇒ this hop IS ingress:
    :func:`start_trace` semantics."""
    if not (isinstance(ctx, dict) and ctx.get("sampled")
            and ctx.get("trace_id")):
        return start_trace(name, **attrs)
    if not _CFG.enabled:
        return NULL_SPAN
    tid = str(ctx["trace_id"])
    parent = ctx.get("parent_id")
    parent = str(parent) if parent else None
    state = SINK._lookup(tid)
    if state is not None:
        sp = Span(name, tid, parent, state, attrs)
        if not state.add(sp):
            return NULL_SPAN
        return sp
    sp = Span(name, tid, parent, None, attrs)
    sp._state = SINK._open(tid, sp)
    return sp


def ingress_span(name: str, traceparent: Optional[str] = None,
                 **attrs) -> "Span | _NullSpan":
    """HTTP-edge ingress: accept a W3C ``traceparent`` header
    (``00-<32 hex trace id>-<16 hex span id>-<flags>``; flags bit 0 =
    sampled). A valid sampled header continues that trace; a valid
    UNsampled one suppresses tracing for the request (the client made the
    head decision); anything else falls back to a local decision."""
    if not _CFG.enabled:
        return NULL_SPAN
    if traceparent:
        parts = traceparent.strip().split("-")
        if len(parts) >= 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
            try:
                tid = parts[1].lower()
                parent = parts[2].lower()
                sampled = bool(int(parts[3], 16) & 1)
                int(tid, 16)
            except ValueError:
                pass
            else:
                if not sampled:
                    return NULL_SPAN
                return from_wire({"trace_id": tid, "parent_id": parent,
                                  "sampled": True}, name, **attrs)
    return start_trace(name, **attrs)


def inject(obj: dict, span=None) -> dict:
    """Attach the (ambient or given) span's wire context to a request
    object in place; no-op for unsampled requests."""
    sp = span if span is not None else current()
    if sp:
        obj["trace"] = sp.wire()
    return obj


# ---- the sink: recent + slowest ring buffers ----


class TraceSink:
    """Process-wide trace store. Two bounded buffers of *finalized* trace
    records — ``recent`` (last N roots to end) and ``slowest`` (top N by
    root duration) — plus the registry of active (not yet finalized)
    states. Active states are bounded too: past ``MAX_ACTIVE_TRACES`` the
    oldest is force-finalized as leaked, so a hop that never ends its
    root cannot grow memory without bound (and the leak is visible in
    ``rbg_trace_traces_total{result="leaked"}``)."""

    def __init__(self, recent: int = 64, slowest: int = 16):
        self._lock = threading.Lock()
        self._recent_cap = recent
        self._slowest_cap = slowest
        self._recent: List[dict] = []
        self._slowest: List[dict] = []
        self._active: "Dict[str, _TraceState]" = {}

    # -- active-state registry (module-internal) --

    def _open(self, trace_id: str, root: Span) -> _TraceState:
        state = _TraceState(trace_id, root)
        evict = None
        with self._lock:
            self._active[trace_id] = state
            if len(self._active) > MAX_ACTIVE_TRACES:
                oldest = next(iter(self._active))
                if oldest != trace_id:
                    evict = self._active.pop(oldest)
        if evict is not None:
            self._finalize(evict, leaked=True)
        return state

    def _lookup(self, trace_id: str) -> Optional[_TraceState]:
        with self._lock:
            return self._active.get(trace_id)

    def _finalize(self, state: _TraceState, leaked: bool = False) -> None:
        with state.lock:
            if state.finalized:
                return
            state.finalized = True
            spans = list(state.spans)
            dropped = state.dropped
        record = _record(state.trace_id, spans, dropped, leaked)
        REGISTRY.inc(names.TRACE_TRACES_TOTAL,
                     result=("leaked" if leaked else
                             "complete" if record["complete"] else
                             "incomplete"))
        with self._lock:
            self._active.pop(state.trace_id, None)
            self._recent.append(record)
            if len(self._recent) > self._recent_cap:
                del self._recent[0]
            self._slowest.append(record)
            self._slowest.sort(key=lambda r: -(r["duration_ms"] or 0.0))
            del self._slowest[self._slowest_cap:]

    # -- operator surface --

    def recent(self, n: int = 10) -> List[dict]:
        with self._lock:
            return list(self._recent[-n:])

    def slowest(self, n: int = 10) -> List[dict]:
        with self._lock:
            return list(self._slowest[:n])

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def snapshot(self, n: int = 10) -> dict:
        return {"recent": self.recent(n), "slowest": self.slowest(n),
                "active": self.active_count()}

    def reset(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slowest.clear()
            self._active.clear()


SINK = TraceSink()


def _record(trace_id: str, spans: List[Span], dropped: int,
            leaked: bool) -> dict:
    """Finalized, JSON-able trace record. ``complete`` = the spans form
    one rooted tree (exactly one local root; every other parent resolves
    in-trace) and every span ended — the ``trace_complete`` invariant the
    stress drills assert. Dropped spans (per-trace bound) are counted
    separately; they are a bounding choice, not an orphan."""
    root = spans[0]
    t0 = root.t0
    ids = {s.span_id for s in spans}
    local_roots = [s for s in spans
                   if s.parent_id is None or s.parent_id not in ids]
    out_spans = []
    for s in sorted(spans, key=lambda s: s.t0):
        out_spans.append({
            "name": s.name, "span_id": s.span_id, "parent_id": s.parent_id,
            "start_ms": round((s.t0 - t0) * 1000.0, 3),
            "duration_ms": (round(s.duration_s * 1000.0, 3)
                            if s.duration_s is not None else None),
            "attrs": dict(s.attrs),
        })
    complete = (not leaked and len(local_roots) == 1
                and all(s.duration_s is not None for s in spans))
    return {
        "trace_id": trace_id,
        "root": root.name,
        "duration_ms": (round(root.duration_s * 1000.0, 3)
                        if root.duration_s is not None else None),
        "spans": out_spans,
        "dropped_spans": dropped,
        "complete": complete,
        "leaked": leaked,
    }


def complete(record: dict) -> bool:
    return bool(record.get("complete"))


def waterfall(record: dict) -> List[str]:
    """Human-readable waterfall for one trace record: tree-indented spans
    with start offset, duration, and attrs — what the stress report and
    the ``traces`` op print for the slowest request."""
    spans = record.get("spans") or []
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {s["span_id"] for s in spans}
    for s in spans:
        parent = s["parent_id"] if s["parent_id"] in ids else None
        by_parent.setdefault(parent, []).append(s)
    lines = [f"trace {record.get('trace_id', '?')} "
             f"({record.get('duration_ms')} ms"
             f"{', INCOMPLETE' if not record.get('complete') else ''})"]

    def emit(parent: Optional[str], depth: int) -> None:
        for s in sorted(by_parent.get(parent, ()),
                        key=lambda s: s["start_ms"]):
            attrs = " ".join(f"{k}={v}" for k, v in
                             sorted(s.get("attrs", {}).items()))
            dur = (f"{s['duration_ms']:.1f}ms"
                   if s["duration_ms"] is not None else "UNFINISHED")
            lines.append(f"{'  ' * depth}{s['name']:<22} "
                         f"+{s['start_ms']:.1f}ms {dur}"
                         + (f"  {attrs}" if attrs else ""))
            emit(s["span_id"], depth + 1)

    emit(None, 1)
    return lines


def hop_coverage(record: dict) -> Optional[float]:
    """Fraction of the root span's duration covered by the union of its
    DIRECT children's intervals — the "hop durations sum to the root"
    acceptance check, overlap-safe. None when it cannot be computed."""
    spans = record.get("spans") or []
    if not spans or record.get("duration_ms") in (None, 0):
        return None
    root = spans[0]
    kids = [s for s in spans
            if s["parent_id"] == root["span_id"]
            and s["duration_ms"] is not None]
    if not kids:
        return 0.0
    iv = sorted((s["start_ms"], s["start_ms"] + s["duration_ms"])
                for s in kids)
    covered, lo, hi = 0.0, iv[0][0], iv[0][1]
    for a, b in iv[1:]:
        if a > hi:
            covered += hi - lo
            lo, hi = a, b
        else:
            hi = max(hi, b)
    covered += hi - lo
    return covered / record["duration_ms"]


def traces_response(n) -> dict:
    """The operator `traces` op payload, shared by the admin plane and the
    engine server: sink snapshot (recent + slowest ring buffers), the
    slowest request's rendered waterfall, and the histogram exemplars that
    link a bad quantile to a trace_id. ``n`` is clamped to [1, 64] and
    tolerates malformed input (wire-facing)."""
    from rbg_tpu.obs.metrics import REGISTRY
    try:
        n = int(n)
    except (TypeError, ValueError):
        n = 10
    resp = SINK.snapshot(max(1, min(n, 64)))
    slowest = resp.get("slowest") or []
    resp["waterfall"] = waterfall(slowest[0]) if slowest else []
    resp["exemplars"] = REGISTRY.exemplars_snapshot()
    return resp
