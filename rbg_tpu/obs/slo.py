"""Per-request SLO judgment: TTFT / TPOT targets, sliding-window
attainment, and goodput.

The serving plane measured TTFT and per-token latency per request and
threw both to the client — nothing ever asked "did that request MEET its
target?". This module closes the loop: every finished request is judged
ONCE against configurable targets (``SLOTargets``: seconds to first
token, seconds per output token after the first; a 0 target disables
that dimension — it always counts as met), the verdicts land in the
``rbg_slo_*`` registry series (counters for scrape pipelines, histograms
for quantiles), and a bounded in-process event window answers the
control-plane questions directly: attainment fractions and **goodput**
(requests/s meeting BOTH targets) over 10/60/300 s windows. "Taming the
Chaos" scales heterogeneous pools off exactly these signals; the
PD-aggregation paper flips agg↔disagg on measured attainment — both
ROADMAP items consume this API.

Judgment sites: ``_BatchService`` (engine side, streaming and blocking —
one judgment per finished request, the ``slo_accounted`` invariant), and
the router (per-role / per-backend attainment from the ingress-stamped
arrival, so retried and failed-over requests are charged their full
wait).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import REGISTRY
# ONE set of standard windows: tracker snapshot keys ("10s"/"60s"/"300s")
# and the sampler's signal windows must stay in lockstep or operator
# surfaces (rbg-tpu top --window) silently stop matching snapshot keys.
from rbg_tpu.obs.timeseries import WINDOWS_S
from rbg_tpu.utils.locktrace import named_lock

DEFAULT_TTFT_S = 2.0
DEFAULT_TPOT_S = 0.5
# Per-tracker event bound: 300 s of judgments at ~13 req/s. Attainment is
# a windowed signal — evicting the tail only shortens the oldest window.
_MAX_EVENTS = 4096
# Gauges are published for this window on every snapshot().
_GAUGE_WINDOW_S = 60.0


@dataclasses.dataclass(frozen=True)
class SLOTargets:
    """Per-request targets. ``ttft_s``: seconds to first token;
    ``tpot_s``: seconds per output token after the first. 0 disables a
    dimension (it always judges as met)."""

    ttft_s: float = DEFAULT_TTFT_S
    tpot_s: float = DEFAULT_TPOT_S

    def as_dict(self) -> dict:
        return {"ttft_s": self.ttft_s, "tpot_s": self.tpot_s}

    def verdict(self, ttft_s, tpot_s) -> Tuple[bool, bool]:
        """THE met-rules, side-effect free: (ttft_ok, tpot_ok). A
        disabled dimension (target <= 0) is always met; a missing
        measurement (None) fails an ENABLED dimension — the one place
        these semantics live (tracker, router, bench all call here)."""
        ttft_ok = self.ttft_s <= 0 or (ttft_s is not None
                                       and ttft_s <= self.ttft_s)
        tpot_ok = self.tpot_s <= 0 or (tpot_s is not None
                                       and tpot_s <= self.tpot_s)
        return ttft_ok, tpot_ok


class _Event:
    __slots__ = ("t", "labels", "ttft_ok", "tpot_ok")

    def __init__(self, t, labels, ttft_ok, tpot_ok):
        self.t = t
        self.labels = labels
        self.ttft_ok = ttft_ok
        self.tpot_ok = tpot_ok


class SLOTracker:
    """One judgment stream (a service, a router). ``judge()`` records the
    verdict + registry series; ``attainment()`` / ``snapshot()`` answer
    windowed fractions and goodput, optionally grouped by a label
    ("role", "backend")."""

    def __init__(self, targets: Optional[SLOTargets] = None,
                 component: str = "service", register: bool = True):
        self.targets = targets or SLOTargets()
        self.component = component
        self._lock = named_lock("obs.slo")
        self._events = collections.deque(maxlen=_MAX_EVENTS)  # guarded_by[obs.slo]
        self._judged = 0          # guarded_by[obs.slo]
        self._met = [0, 0, 0]     # guarded_by[obs.slo] (ttft, tpot, both)
        if register:
            register_tracker(self)

    # -- judgment --

    def judge(self, ttft_s: float, tpot_s: float, **labels) -> dict:
        """Judge ONE finished request. Returns the verdict dict; publishes
        the rbg_slo_* counter/histogram series labeled with ``labels`` +
        this tracker's component."""
        ttft_ok, tpot_ok = self.targets.verdict(ttft_s, tpot_s)
        both = ttft_ok and tpot_ok
        ev = _Event(time.monotonic(), tuple(sorted(labels.items())),
                    ttft_ok, tpot_ok)
        with self._lock:
            self._events.append(ev)
            self._judged += 1
            self._met[0] += ttft_ok
            self._met[1] += tpot_ok
            self._met[2] += both
        lbl = dict(labels, component=self.component)
        REGISTRY.inc(names.SLO_JUDGED_TOTAL, **lbl)
        if ttft_ok:
            REGISTRY.inc(names.SLO_TTFT_MET_TOTAL, **lbl)
        if tpot_ok:
            REGISTRY.inc(names.SLO_TPOT_MET_TOTAL, **lbl)
        if both:
            REGISTRY.inc(names.SLO_GOODPUT_TOTAL, **lbl)
        REGISTRY.observe(names.SLO_TTFT_SECONDS, ttft_s, **lbl)
        REGISTRY.observe(names.SLO_TPOT_SECONDS, tpot_s, **lbl)
        return {"ttft_ok": ttft_ok, "tpot_ok": tpot_ok, "goodput": both}

    def judged_total(self) -> int:
        with self._lock:
            return self._judged

    def totals(self) -> dict:
        """Lifetime verdict counts (bounded only by int width — these are
        counters, not the event window)."""
        with self._lock:
            judged, (ttft, tpot, both) = self._judged, tuple(self._met)
        return {"judged": judged, "ttft_met": ttft, "tpot_met": tpot,
                "goodput": both}

    # -- windows --

    @staticmethod
    def _frac(num: int, den: int) -> Optional[float]:
        return round(num / den, 4) if den else None

    def attainment(self, window_s: float = 60.0,
                   group_by: Optional[Iterable[str]] = None,
                   now: Optional[float] = None) -> Dict[str, dict]:
        """Windowed attainment, grouped by the given label names (or one
        ``"all"`` group). Each group carries judged count, ttft/tpot
        attainment fractions (None when nothing was judged), and
        goodput_rps over the window."""
        anchor = time.monotonic() if now is None else now
        cutoff = anchor - window_s
        keys = tuple(group_by or ())
        with self._lock:
            events = [e for e in self._events if e.t >= cutoff]
        groups: Dict[str, List[_Event]] = {}
        for e in events:
            if keys:
                lbl = dict(e.labels)
                gk = ",".join(f"{k}={lbl.get(k, '')}" for k in keys)
            else:
                gk = "all"
            groups.setdefault(gk, []).append(e)
        out = {}
        for gk, evs in sorted(groups.items()):
            n = len(evs)
            good = sum(1 for e in evs if e.ttft_ok and e.tpot_ok)
            out[gk] = {
                "judged": n,
                "ttft_attainment": self._frac(
                    sum(1 for e in evs if e.ttft_ok), n),
                "tpot_attainment": self._frac(
                    sum(1 for e in evs if e.tpot_ok), n),
                "goodput_attainment": self._frac(good, n),
                "goodput_rps": round(good / window_s, 4),
            }
        return out

    def snapshot(self, windows: Tuple[float, ...] = WINDOWS_S,
                 group_by: Optional[Iterable[str]] = None,
                 now: Optional[float] = None) -> dict:
        """Targets + totals + per-window attainment; publishes the 60 s
        overall attainment/goodput gauges for scrape pipelines."""
        out = {
            "component": self.component,
            "targets": self.targets.as_dict(),
            "totals": self.totals(),
            "windows": {f"{int(w)}s": self.attainment(w, group_by=group_by,
                                                      now=now)
                        for w in windows},
        }
        overall = self.attainment(_GAUGE_WINDOW_S, now=now).get("all")
        if overall:
            if overall["ttft_attainment"] is not None:
                REGISTRY.set_gauge(names.SLO_TTFT_ATTAINMENT,
                                   overall["ttft_attainment"],
                                   component=self.component)
            if overall["tpot_attainment"] is not None:
                REGISTRY.set_gauge(names.SLO_TPOT_ATTAINMENT,
                                   overall["tpot_attainment"],
                                   component=self.component)
            REGISTRY.set_gauge(names.SLO_GOODPUT_RPS,
                               overall["goodput_rps"],
                               component=self.component)
        return out


# ---- process-wide tracker registry -----------------------------------------
#
# The operator surface (admin `slo` op, engine-server `slo` data op, the
# stress reports) pulls every live tracker in-process. Bounded: only the
# newest _MAX_TRACKERS survive — a test suite churning services must not
# accumulate dead trackers forever.

_MAX_TRACKERS = 16
_TRACKERS: List[SLOTracker] = []
_REG_LOCK = threading.Lock()


def register_tracker(tracker: SLOTracker) -> None:
    with _REG_LOCK:
        _TRACKERS.append(tracker)
        del _TRACKERS[:-_MAX_TRACKERS]


def trackers() -> List[SLOTracker]:
    with _REG_LOCK:
        return list(_TRACKERS)


def reset_trackers() -> None:
    with _REG_LOCK:
        _TRACKERS.clear()


def slo_response(window=None) -> dict:
    """The operator ``slo`` op payload, shared by the admin plane and the
    engine server (same clamped-response contract as ``traces_response``):
    per-tracker attainment snapshots plus the windowed signals the
    timeseries sampler holds. ``window`` (seconds) picks the headline
    signals window; malformed input falls back to 60 and is clamped to
    [1, 3600] — wire-facing, must not throw."""
    from rbg_tpu.obs import timeseries
    try:
        w = float(window)
    except (TypeError, ValueError):
        w = 60.0
    w = max(1.0, min(w, 3600.0))
    sampler = timeseries.get_sampler()

    def signals(window_s: float) -> dict:
        def r(v, nd=4):
            return round(v, nd) if v is not None else None
        return {
            "requests_per_s": r(sampler.rate(
                names.SERVING_REQUESTS_FINISHED_TOTAL, window_s)),
            "tokens_per_s": r(sampler.rate(
                names.SERVING_TOKENS_TOTAL, window_s), 2),
            "shed_per_s": r(sampler.rate(
                names.SERVING_SHED_TOTAL, window_s)),
            "deadline_exceeded_per_s": r(sampler.rate(
                names.SERVING_DEADLINE_EXCEEDED_TOTAL, window_s)),
            "goodput_per_s": r(sampler.rate(
                names.SLO_GOODPUT_TOTAL, window_s)),
            "queue_depth_mean": r(sampler.mean_observed(
                names.SERVING_QUEUE_DEPTH, window_s), 2),
            "occupancy_mean": r(sampler.mean_observed(
                names.SERVING_BATCH_OCCUPANCY, window_s)),
            "ttft_mean_s": r(sampler.mean_observed(
                names.SLO_TTFT_SECONDS, window_s)),
            "tpot_mean_s": r(sampler.mean_observed(
                names.SLO_TPOT_SECONDS, window_s)),
        }

    def cache_tiers(window_s: float) -> dict:
        """Per-tier KV cache-hierarchy panel (engine/kvtier.py): resident
        pages/bytes (live gauges) plus windowed hit / spill / promote /
        evict rates — empty when no tier ever published (host tier off)."""
        def r(v, nd=4):
            return round(v, nd) if v is not None else None
        tiers = {}
        for tier in sorted(REGISTRY.label_values(names.KVC_TIER_PAGES,
                                                 "tier")):
            tiers[tier] = {
                "pages": REGISTRY.gauge(names.KVC_TIER_PAGES, tier=tier),
                "bytes": REGISTRY.gauge(names.KVC_TIER_BYTES, tier=tier),
                "hits_per_s": r(sampler.rate(
                    names.KVC_TIER_HITS_TOTAL, window_s, tier=tier)),
                "evicted_pages_per_s": r(sampler.rate(
                    names.KVC_TIER_EVICTED_PAGES_TOTAL, window_s,
                    tier=tier)),
            }
        if not tiers:
            return {}
        return {
            "tiers": tiers,
            "misses_per_s": r(sampler.rate(
                names.KVC_TIER_MISSES_TOTAL, window_s)),
            "spill_pages_per_s": r(sampler.rate(
                names.KVC_TIER_SPILLED_PAGES_TOTAL, window_s)),
            "promote_pages_per_s": r(sampler.rate(
                names.KVC_TIER_PROMOTED_PAGES_TOTAL, window_s)),
        }

    return {
        "window_s": w,
        "sampler": sampler.stats(),
        "signals": signals(w),
        "signals_by_window": {f"{int(ws)}s": signals(ws)
                              for ws in timeseries.WINDOWS_S},
        "cache": cache_tiers(w),
        "trackers": [t.snapshot(group_by=("role",))
                     for t in trackers()],
    }
