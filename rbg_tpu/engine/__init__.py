from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.engine import Engine, Request, StepEvent
from rbg_tpu.engine.kvcache import PageAllocator, PagedKVCache
from rbg_tpu.engine.radix_cache import RadixCache

__all__ = [
    "Engine", "EngineConfig", "SamplingParams", "Request", "StepEvent",
    "PageAllocator", "PagedKVCache", "RadixCache",
]
