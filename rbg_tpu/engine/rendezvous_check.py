"""Rendezvous check worker: prove the control plane's injected JAX
distributed-init contract actually forms a working multi-process JAX job.

Runs as a leaderWorker role's pod: consumes RBG_JAX_COORDINATOR_ADDRESS /
RBG_JAX_NUM_PROCESSES / RBG_JAX_PROCESS_ID exactly the way an engine would
(reference analog: SGLang consuming RBG_LWP_* as --dist-init-addr/--nnodes/
--node-rank in examples/inference/pd-disagg-leader-worker.yaml), calls
``jax.distributed.initialize``, performs a cross-process collective, and
writes the result to ``RBG_RENDEZVOUS_OUT``. Serves the standard health op so
the executor's readiness probe passes.

Local-mode address resolution: pod FQDNs aren't DNS here, so when a registry
path is present the coordinator's host part resolves to 127.0.0.1 (same-host
processes). On GKE the FQDN resolves via the headless service instead.
"""

from __future__ import annotations

import json
import os
import socketserver
import sys
import threading


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    port = int(os.environ.get("RBG_SERVE_PORT", "9400"))
    state = {"ok": False, "detail": "initializing"}

    from rbg_tpu.engine.protocol import recv_msg, send_msg

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            while True:
                try:
                    obj, _, _ = recv_msg(self.request)
                except Exception:
                    return
                if obj is None:
                    return
                send_msg(self.request, {"ok": True, "rendezvous": dict(state)})

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", port), Handler)
    srv.allow_reuse_address = True
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    print(f"rendezvous-check listening on {port}", flush=True)

    coordinator = os.environ["RBG_JAX_COORDINATOR_ADDRESS"]
    num = int(os.environ["RBG_JAX_NUM_PROCESSES"])
    pid = int(os.environ["RBG_JAX_PROCESS_ID"])
    if os.environ.get("RBG_REGISTRY_PATH"):
        coordinator = "127.0.0.1:" + coordinator.rsplit(":", 1)[1]

    import jax
    jax.distributed.initialize(coordinator, num_processes=num, process_id=pid)
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    # Leader broadcasts the group identity; everyone checks the device count.
    group = os.environ.get("RBG_GROUP_NAME", "")
    payload = jnp.asarray([float(len(group)), float(pid)])
    leader_payload = multihost_utils.broadcast_one_to_all(payload)
    result = {
        "process_id": pid,
        "num_processes": num,
        "global_devices": jax.device_count(),
        "leader_group_len": int(leader_payload[0]),
        "leader_pid": int(leader_payload[1]),
    }
    state.update(ok=True, detail="rendezvous complete", **result)
    out = os.environ.get("RBG_RENDEZVOUS_OUT")
    if out:
        with open(f"{out}.{pid}", "w") as f:
            json.dump(result, f)
    print(f"rendezvous ok: {result}", flush=True)
    threading.Event().wait()  # serve health until terminated
    return 0


if __name__ == "__main__":
    sys.exit(main())
