"""Radix prefix cache: share KV pages across requests with common prefixes.

SGLang's signature serving optimization (RadixAttention), page-granular for
TPU: only whole frozen pages are shared (no copy-on-write on device), so a
cache hit contributes ``(match_len // page_size) * page_size`` reusable
tokens. Eviction is LRU over leaves, integrated with the PageAllocator's
refcounts: a cached page is freed only when no running request references it.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class _Node:
    __slots__ = ("key", "pages", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], pages: List[int], parent):
        self.key = key           # token chunk (page_size tokens per page)
        self.pages = pages       # physical page ids, len == len(key)/page_size
        self.children: Dict[int, "_Node"] = {}
        self.parent = parent
        self.last_used = time.monotonic()


class RadixCache:
    def __init__(self, allocator, page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        self.root = _Node((), [], None)
        self._nodes = 0
        self._cached_pages = 0

    # ---- lookup ----

    def match(self, tokens: List[int]) -> Tuple[int, List[int]]:
        """Longest page-aligned cached prefix. Returns (matched_tokens,
        pages). Caller must ``allocator.share`` via ``lock()`` if it uses
        them (we do it here for atomicity)."""
        ps = self.page_size
        node = self.root
        pages: List[int] = []
        i = 0
        n = len(tokens)
        while True:
            node.last_used = time.monotonic()
            if i >= n:
                break
            child = node.children.get(tokens[i])
            if child is None:
                break
            # Page-granular partial-node matching: take every fully-agreeing
            # page of the child, even when the query ends inside its key.
            kl = len(child.key)
            limit = min(kl, n - i)
            common = 0
            while common < limit and child.key[common] == tokens[i + common]:
                common += 1
            full_pages = common // ps
            pages.extend(child.pages[:full_pages])
            i += full_pages * ps
            if common < kl:
                break  # diverged or query exhausted inside this node
            node = child
        if pages:
            self.allocator.share(pages)  # lock for the caller
        return i, pages

    def peek(self, tokens: List[int]) -> int:
        """Advisory matched-token depth: no page sharing, no LRU touch.
        The admission-side TTFT predictor reads this from a submitter
        thread while the loop thread owns the trie — pure dict reads,
        tolerant of a stale answer (callers wrap it best-effort)."""
        ps = self.page_size
        node = self.root
        i, n = 0, len(tokens)
        while i < n:
            child = node.children.get(tokens[i])
            if child is None:
                break
            kl = len(child.key)
            limit = min(kl, n - i)
            common = 0
            while common < limit and child.key[common] == tokens[i + common]:
                common += 1
            i += (common // ps) * ps
            if common < kl:
                break
            node = child
        return i

    # ---- insert ----

    def insert(self, tokens: List[int], pages: List[int]) -> None:
        """Insert a finished sequence's page-aligned prefix. Takes a NEW
        reference on the inserted pages (caller keeps its own and releases it
        separately)."""
        ps = self.page_size
        usable = (len(tokens) // ps) * ps
        tokens = tokens[:usable]
        pages = pages[:usable // ps]
        node = self.root
        i = 0
        while i < len(tokens):
            child = node.children.get(tokens[i])
            if child is None:
                key = tuple(tokens[i:])
                new_pages = pages[i // ps:]
                self.allocator.share(new_pages)
                node.children[tokens[i]] = _Node(key, list(new_pages), node)
                self._nodes += 1
                self._cached_pages += len(new_pages)
                return
            kl = len(child.key)
            if tuple(tokens[i:i + kl]) == child.key:
                node = child
                node.last_used = time.monotonic()
                i += kl
                continue
            # Diverging inside a node: split at the longest common
            # page-aligned boundary.
            common_pages = 0
            for j in range(min(kl, len(tokens) - i) // ps):
                if child.key[j * ps:(j + 1) * ps] == tuple(tokens[i + j * ps:i + (j + 1) * ps]):
                    common_pages += 1
                else:
                    break
            if common_pages == 0:
                return  # nothing page-aligned in common under this child
            split = common_pages * ps
            mid = _Node(child.key[:split], child.pages[:common_pages], node)
            child.key = child.key[split:]
            child.pages = child.pages[common_pages:]
            child.parent = mid
            mid.children[child.key[0]] = child
            node.children[tokens[i]] = mid
            self._nodes += 1
            node = mid
            i += split

    # ---- eviction ----

    def evict(self, need_pages: int, on_evict=None) -> int:
        """Evict LRU leaves until ``need_pages`` pages were released (or the
        tree is empty). Returns pages released. Pages still referenced by
        running requests survive via refcounts.

        ``on_evict(prefix_tokens, pages)`` — called per evicted leaf
        BEFORE its pages are released, with the FULL root→leaf token
        prefix — is the device→host spill hook: the page contents are
        still valid on device at that point, so the host tier can copy
        them out before the allocator may recycle the ids."""
        released = 0
        while released < need_pages:
            leaf = self._lru_leaf()
            if leaf is None:
                break
            if on_evict is not None and leaf.pages:
                on_evict(self._full_prefix(leaf), list(leaf.pages))
            free_before = self.allocator.free_pages
            self.allocator.release(leaf.pages)
            # Only pages whose refcount hit zero actually freed — pages still
            # pinned by running requests don't count toward the goal.
            released += self.allocator.free_pages - free_before
            parent = leaf.parent
            parent.children = {
                t: c for t, c in parent.children.items() if c is not leaf
            }
            self._nodes -= 1
            self._cached_pages -= len(leaf.pages)
        return released

    @staticmethod
    def _full_prefix(node: "_Node") -> List[int]:
        """Root→node token prefix (page-aligned by construction — every
        node's pages cover its whole key)."""
        parts = []
        while node is not None and node.key:
            parts.append(node.key)
            node = node.parent
        out: List[int] = []
        for key in reversed(parts):
            out.extend(key)
        return out

    def _lru_leaf(self) -> Optional[_Node]:
        best = None
        stack = [self.root]
        while stack:
            node = stack.pop()
            kids = list(node.children.values())
            if not kids and node is not self.root:
                if best is None or node.last_used < best.last_used:
                    best = node
            stack.extend(kids)
        return best

    @property
    def num_nodes(self) -> int:
        return self._nodes

    @property
    def cached_pages(self) -> int:
        """Pages this cache currently indexes — the DEVICE tier's
        population for the rbg_kvcache_tier_pages accounting."""
        return self._cached_pages
