"""Serving benchmark: Poisson open-loop load against an engine, measuring
the serving SLOs the north star is written in (BASELINE.json: tokens/sec
AND p50 TTFT) — TTFT / inter-token latency / throughput percentiles.

Reference context: the reference's only perf apparatus is the
control-plane stress harness (``test/stress``); engine-side serving SLOs
are delegated to the engines it orchestrates. This harness closes that
gap for ours: an sglang.bench_serving analog that drives EITHER an
in-process ``EngineService`` (default — measures the engine itself) or a
remote server over the wire (``--addr``; measures the full role stack).

Open-loop (arrivals don't wait for completions) so the measured latencies
reflect queueing at the offered rate — the honest serving-SLO
methodology; a closed loop understates latency at saturation.

Usage:
    python -m rbg_tpu.engine.bench_serving --requests 64 --rate 16 \
        --model tiny --input-len 32 --output-len 32 [--addr host:port]

Prints one human table and, with ``--json``, one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import List, Optional


def _percentile(xs: List[float], p: float) -> float:
    if not xs:
        return float("nan")
    ys = sorted(xs)
    i = min(len(ys) - 1, max(0, int(round(p / 100 * (len(ys) - 1)))))
    return ys[i]


class _Result:
    __slots__ = ("ttft_s", "itl_s", "n_tokens", "latency_s", "ok")

    def __init__(self):
        self.ttft_s: Optional[float] = None
        self.itl_s: List[float] = []
        self.n_tokens = 0
        self.latency_s = 0.0
        self.ok = False


def _drive_inprocess(args, prompts, arrivals):
    """Submit through an EngineService; per-token timing via step events."""
    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.service import EngineService

    svc = EngineService(EngineConfig(
        model=args.model, page_size=args.page_size, num_pages=args.num_pages,
        max_seq_len=args.max_seq_len, max_batch=args.max_batch,
        use_pallas=args.use_pallas, multi_step=args.multi_step,
        speculative=args.speculative,
        # Honest prefills: warmup prompts must not seed a prefix cache the
        # measured requests then hit.
        enable_radix_cache=False))
    # Compile every jit bucket variant up front (prefill B, finish-sample
    # Bs, decode B — one wave per bucket size), so measured TTFT/ITL
    # excludes XLA compilation. Full-batch draining alone is NOT enough:
    # a bucket first hit mid-measurement was observed as a 9x throughput
    # swing between identical runs.
    svc.warmup(args.input_len)

    results = [_Result() for _ in prompts]
    lock = threading.Lock()
    done = threading.Event()
    outstanding = [len(prompts)]

    def one(i):
        res = results[i]
        t0 = time.perf_counter()
        p = svc.submit_async(prompts[i],
                             SamplingParams(max_new_tokens=args.output_len))
        try:
            last = [t0]

            # Poll tokens for ITL (the service appends as events arrive).
            while not p.done.wait(0.002):
                now = time.perf_counter()
                n = len(p.tokens)
                if n > res.n_tokens:
                    if res.ttft_s is None:
                        res.ttft_s = now - t0
                    else:
                        res.itl_s.append((now - last[0]) / (n - res.n_tokens))
                    res.n_tokens = n
                    last[0] = now
            res.n_tokens = len(p.tokens)
            if res.ttft_s is None and p.t_first:
                res.ttft_s = p.t_first - p.t_submit
            res.latency_s = time.perf_counter() - t0
            res.ok = p.error is None
        finally:
            with lock:
                outstanding[0] -= 1
                if not outstanding[0]:
                    done.set()

    t_start = time.perf_counter()
    for i, at in enumerate(arrivals):
        delay = t_start + at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        threading.Thread(target=one, args=(i,), daemon=True).start()
    done.wait()
    wall = time.perf_counter() - t_start
    svc.stop()
    return results, wall


def _drive_remote(args, prompts, arrivals):
    """Streamed requests over the wire protocol against --addr."""
    import socket

    from rbg_tpu.engine.protocol import recv_msg, send_msg

    results = [_Result() for _ in prompts]
    done = threading.Event()
    lock = threading.Lock()
    outstanding = [len(prompts)]

    def one(i):
        res = results[i]
        t0 = time.perf_counter()
        try:
            host, port = args.addr.rsplit(":", 1)
            req = {"op": "generate", "prompt": prompts[i],
                   "max_new_tokens": args.output_len, "stream": True}
            token = getattr(args, "token", None)
            if token:
                req["token"] = token
            with socket.create_connection((host, int(port)),
                                          timeout=300) as s:
                send_msg(s, req)
                last = t0
                while True:
                    frame, _, _ = recv_msg(s)
                    if frame is None or "error" in (frame or {}):
                        break
                    toks = frame.get("tokens", [])
                    now = time.perf_counter()
                    if toks:
                        if res.ttft_s is None:
                            res.ttft_s = now - t0
                        else:
                            res.itl_s.append((now - last) / len(toks))
                        res.n_tokens += len(toks)
                        last = now
                    if frame.get("done"):
                        res.ok = True
                        break
            res.latency_s = time.perf_counter() - t0
        except OSError:
            pass
        finally:
            with lock:
                outstanding[0] -= 1
                if not outstanding[0]:
                    done.set()

    t_start = time.perf_counter()
    for i, at in enumerate(arrivals):
        delay = t_start + at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        threading.Thread(target=one, args=(i,), daemon=True).start()
    done.wait()
    return results, time.perf_counter() - t_start


def run(args) -> dict:
    import numpy as np

    rng = np.random.default_rng(args.seed)
    # Synthetic prompts: random ids in a safe sub-vocab range.
    prompts = [rng.integers(1, 200, size=args.input_len).tolist()
               for _ in range(args.requests)]
    # Poisson process: exponential gaps at the offered rate.
    gaps = rng.exponential(1.0 / args.rate, size=args.requests)
    gaps[0] = 0.0
    arrivals = np.cumsum(gaps).tolist()

    if args.addr:
        results, wall = _drive_remote(args, prompts, arrivals)
    else:
        results, wall = _drive_inprocess(args, prompts, arrivals)

    ok = [r for r in results if r.ok]
    ttfts = [r.ttft_s for r in ok if r.ttft_s is not None]
    itls = [x for r in ok for x in r.itl_s]
    lats = [r.latency_s for r in ok]
    total_tokens = sum(r.n_tokens for r in ok)

    # SLO judgment (optional): per-request TTFT + TPOT ((e2e - ttft) /
    # (n - 1), the decode-side per-token latency) against the targets —
    # goodput is completions/s that met BOTH. The met-rules (0 disables
    # a dimension, a missing measurement fails an enabled one) live in
    # ONE place: SLOTargets.verdict, the same rules the serving plane's
    # rbg_slo_* series stand on.
    from rbg_tpu.obs.slo import SLOTargets
    ttft_target = float(getattr(args, "slo_ttft_s", 0.0) or 0.0)
    tpot_target = float(getattr(args, "slo_tpot_s", 0.0) or 0.0)
    targets = SLOTargets(ttft_s=ttft_target, tpot_s=tpot_target)

    def _tpot(r):
        if r.n_tokens > 1 and r.ttft_s is not None:
            return (r.latency_s - r.ttft_s) / (r.n_tokens - 1)
        return 0.0 if r.ttft_s is not None else None

    def _verdict(r):
        return targets.verdict(r.ttft_s, _tpot(r))

    out = {
        "requests": args.requests,
        "completed": len(ok),
        "offered_rate_rps": args.rate,
        "duration_s": round(wall, 3),
        "output_tok_per_s": round(total_tokens / wall, 1) if wall else 0.0,
        "ttft_s": {"p50": round(_percentile(ttfts, 50), 4),
                   "p90": round(_percentile(ttfts, 90), 4),
                   "p99": round(_percentile(ttfts, 99), 4)},
        "itl_ms": {"p50": round(_percentile(itls, 50) * 1e3, 2),
                   "p90": round(_percentile(itls, 90) * 1e3, 2),
                   "p99": round(_percentile(itls, 99) * 1e3, 2)},
        "e2e_s": {"p50": round(_percentile(lats, 50), 3),
                  "p99": round(_percentile(lats, 99), 3)},
    }
    if ttft_target > 0 or tpot_target > 0:
        verdicts = [_verdict(r) for r in ok]
        good = sum(1 for t_ok, p_ok in verdicts if t_ok and p_ok)
        out["slo"] = {
            "ttft_target_s": ttft_target, "tpot_target_s": tpot_target,
            "ttft_attainment": round(
                sum(1 for t_ok, _ in verdicts if t_ok) / len(ok), 4)
                if ok else None,
            "tpot_attainment": round(
                sum(1 for _, p_ok in verdicts if p_ok) / len(ok), 4)
                if ok else None,
            "goodput_fraction": round(good / len(ok), 4) if ok else None,
        }
        out["goodput_rps"] = round(good / wall, 3) if wall else 0.0
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("rbg-tpu serving benchmark")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--rate", type=float, default=16.0,
                    help="offered request rate (Poisson), req/s")
    ap.add_argument("--input-len", type=int, default=32)
    ap.add_argument("--output-len", type=int, default=32)
    ap.add_argument("--model", default="tiny")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=512)
    ap.add_argument("--max-seq-len", type=int, default=512)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--use-pallas", default="auto")
    ap.add_argument("--multi-step", type=int, default=1)
    ap.add_argument("--speculative", default="off")
    ap.add_argument("--addr", default="",
                    help="benchmark a remote engine/router instead of "
                         "in-process (host:port)")
    ap.add_argument("--token", default=os.environ.get("RBG_DATA_TOKEN", ""),
                    help="data-plane bearer token for --addr targets "
                         "(default: $RBG_DATA_TOKEN)")
    ap.add_argument("--slo-ttft-s", type=float, default=0.0,
                    help="TTFT target: emit goodput_rps + attainment "
                         "(0 = no TTFT judgment)")
    ap.add_argument("--slo-tpot-s", type=float, default=0.0,
                    help="per-output-token latency target for goodput "
                         "(0 = no TPOT judgment)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line instead of the table")
    args = ap.parse_args(argv)
    out = run(args)
    if args.json:
        print(json.dumps(out))
        return 0
    print(f"completed {out['completed']}/{out['requests']} requests "
          f"in {out['duration_s']}s @ offered {out['offered_rate_rps']} rps")
    print(f"throughput  {out['output_tok_per_s']} output tok/s")
    print(f"ttft        p50 {out['ttft_s']['p50']}s   p90 "
          f"{out['ttft_s']['p90']}s   p99 {out['ttft_s']['p99']}s")
    print(f"itl         p50 {out['itl_ms']['p50']}ms  p90 "
          f"{out['itl_ms']['p90']}ms  p99 {out['itl_ms']['p99']}ms")
    print(f"e2e         p50 {out['e2e_s']['p50']}s   p99 "
          f"{out['e2e_s']['p99']}s")
    if "goodput_rps" in out:
        slo = out["slo"]
        print(f"goodput     {out['goodput_rps']} req/s meeting ttft<="
              f"{slo['ttft_target_s']}s tpot<={slo['tpot_target_s']}s "
              f"(fraction {slo['goodput_fraction']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
