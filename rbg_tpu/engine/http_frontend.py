"""OpenAI-compatible HTTP front end with SSE streaming.

Reference context: the reference's serving examples assume engines speak
HTTP (``examples/inference/pd-disagg-leader-worker.yaml`` router args
``http://...:8000``); VERDICT r3 missing #7. This process is the public
edge of a serving group:

    client ──HTTP/SSE──> http_frontend ──TCP──> router ──> prefill/decode

Endpoints:

* ``POST /v1/completions``       — OpenAI Completions (+``stream``)
* ``POST /v1/chat/completions``  — OpenAI Chat (+``stream``)
* ``GET  /v1/models``            — the served model
* ``GET  /healthz``              — liveness + backend reachability

Tokenization lives HERE (encode prompts, incrementally detokenize streamed
ids — ``tokenizer.IncrementalDetokenizer``); the internal TCP protocol
stays token-id based (PD transfer unchanged)."""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from rbg_tpu.api.errors import CODE_HTTP_ETYPE as _CODE_ETYPE
from rbg_tpu.api.errors import CODE_HTTP_STATUS as _CODE_STATUS
from rbg_tpu.engine.config import SamplingParams
from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg
from rbg_tpu.engine.tokenizer import IncrementalDetokenizer, load_tokenizer
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs import trace

# Structured backend rejections → HTTP statuses and OpenAI-style error
# types: the mapping lives with the code catalog (api/errors.py) so the
# edge and the catalog cannot drift apart.
MAX_TIMEOUT_S = 600.0


def _chat_to_prompt(messages: List[dict]) -> str:
    """Minimal chat template: role-tagged lines + assistant cue. Real
    deployments pass --tokenizer-path whose chat template could be applied;
    byte-level serving uses this plain form."""
    lines = [f"{m.get('role', 'user')}: {m.get('content', '')}"
             for m in messages]
    lines.append("assistant:")
    return "\n".join(lines)


class _State:
    def __init__(self, args):
        self.backend = args.backend
        self.model = args.model
        self.tokenizer = load_tokenizer(args.tokenizer_path or None)
        self.default_max_tokens = args.default_max_tokens
        # SSE liveness: comment frames every this-many idle seconds so
        # clients behind the router tier detect a dead hop in seconds
        # instead of waiting out TCP timeouts. 0 disables.
        self.sse_keepalive_s = float(
            getattr(args, "sse_keepalive_s", 15.0) or 0.0)
        # Data-plane bearer token attached to every backend call when the
        # serving wire is token-gated (RBG_DATA_TOKEN; VERDICT r4 #6).
        self.data_token = os.environ.get("RBG_DATA_TOKEN") or None

    def backend_req(self, req: dict) -> dict:
        if self.data_token:
            req["token"] = self.data_token
        # Trace context rides the wire next to the token: the router (or a
        # unified engine server) continues this edge's http.request span.
        # No-op when the request is unsampled or tracing is off.
        return trace.inject(req)


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "rbg-tpu"

    def log_message(self, *a):
        pass

    # ---- plumbing ----

    def _json(self, code: int, body: dict, extra_headers=None):
        data = json.dumps(body).encode()
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        rid = getattr(self, "_request_id", None)
        if rid:
            self.send_header("X-Request-Id", rid)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, message: str, etype: str = "invalid_request_error",
               retry_after_s=None):
        headers = None
        if retry_after_s is not None:
            # HTTP Retry-After is integer seconds; round UP so a 0.3 s
            # hint never becomes "retry immediately".
            headers = {"Retry-After":
                       str(max(1, int(-(-float(retry_after_s) // 1))))}
        self._json(code, {"error": {"message": message, "type": etype}},
                   extra_headers=headers)

    def _backend_error(self, resp: dict):
        """Map a backend error reply: structured rejection codes get their
        HTTP status + Retry-After; anything else stays a 502."""
        resp = resp or {}
        status = _CODE_STATUS.get(resp.get("code"))
        if status is not None:
            return self._error(status, resp.get("error", "rejected"),
                               _CODE_ETYPE[resp["code"]],
                               retry_after_s=resp.get("retry_after_s"))
        return self._error(502, resp.get("error", "no response"),
                           "server_error")

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw)

    # ---- routes ----

    def do_GET(self):
        # Re-stamp per request: the handler instance persists across a
        # keep-alive connection, so a stale id from an earlier POST must
        # not be echoed on this response.
        self._request_id = self.headers.get("X-Request-Id")
        st: _State = self.server.state
        if self.path == "/healthz":
            ok, draining = True, False
            try:
                h, _, _ = request_once(st.backend, {"op": "health"}, timeout=5)
                ok = bool(h and (h.get("ok") or "pd" in h))
                draining = bool(h and h.get("draining"))
            except OSError:
                ok = False
            # A draining backend is alive but should be rotated out: 503
            # flips readiness while in-flight streams keep finishing.
            return self._json(200 if ok and not draining else 503,
                              {"ok": ok, "draining": draining,
                               "backend": st.backend})
        if self.path == "/v1/models":
            return self._json(200, {"object": "list", "data": [
                {"id": st.model, "object": "model", "owned_by": "rbg-tpu"}]})
        return self._error(404, f"no route {self.path}")

    def do_POST(self):
        # Request identity + trace ingress (alongside the PR-2 deadline):
        # accept the caller's X-Request-Id (stamp one otherwise — it is
        # echoed on every response), accept a W3C ``traceparent`` header,
        # and make the http.request span ambient so the whole handler —
        # backend_req injection included — rides under it.
        self._request_id = (self.headers.get("X-Request-Id")
                            or f"req-{uuid.uuid4().hex[:16]}")
        self._status = 0
        span = trace.ingress_span(obs_names.SPAN_HTTP_REQUEST,
                                  traceparent=self.headers.get("traceparent"),
                                  path=self.path,
                                  request_id=self._request_id)
        try:
            with trace.use_span(span):
                self._handle_post()
        finally:
            span.end(status=self._status)

    def _handle_post(self):
        st: _State = self.server.state
        try:
            body = self._body()
        except json.JSONDecodeError as e:
            return self._error(400, f"bad JSON: {e}")
        if self.path == "/v1/completions":
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = "".join(prompt)
            return self._complete(st, body, prompt, chat=False)
        if self.path == "/v1/chat/completions":
            messages = body.get("messages") or []
            text = None
            if hasattr(st.tokenizer, "apply_chat_template"):
                try:
                    # The model's OWN template when the tokenizer ships one.
                    text = st.tokenizer.apply_chat_template(messages)
                except Exception as e:  # jinja TemplateError/TypeError etc.
                    return self._error(
                        400, f"messages rejected by the model's chat "
                             f"template: {e}")
            if text is None:
                text = _chat_to_prompt(messages)
            return self._complete(st, body, text, chat=True)
        if self.path == "/v1/embeddings":
            return self._embeddings(st, body)
        return self._error(404, f"no route {self.path}")

    def _embeddings(self, st: _State, body: dict):
        inputs = body.get("input")
        if isinstance(inputs, str):
            inputs = [inputs]
        if (not isinstance(inputs, list) or not inputs
                or not all(isinstance(s, str) and s for s in inputs)):
            return self._error(400, "input must be a non-empty string or "
                                    "non-empty list of non-empty strings")
        if len(inputs) > 256:
            return self._error(400, "input list too large (max 256 per "
                                    "request)")
        # Tokenize EDGE-side (same contract as completions — the wire stays
        # token-ids; the backend's fallback tokenizer must never see text),
        # and ship the whole batch as ONE op → one batched forward.
        prompts = [st.tokenizer.encode(s, add_bos=False) for s in inputs]
        try:
            resp, _, _ = request_once(st.backend,
                                      st.backend_req({"op": "embed",
                                                      "prompts": prompts}),
                                      timeout=300)
        except OSError as e:
            return self._error(502, f"backend: {e}", "server_error")
        if resp is None or "error" in (resp or {}):
            return self._backend_error(resp)
        total = sum(len(p) for p in prompts)
        data = [{"object": "embedding", "index": i, "embedding": v}
                for i, v in enumerate(resp["embeddings"])]
        return self._json(200, {
            "object": "list", "model": st.model, "data": data,
            "usage": {"prompt_tokens": total, "total_tokens": total}})

    # ---- completion core ----

    @staticmethod
    def _parse_stops(body: dict) -> List[str]:
        stop = body.get("stop")
        if stop is None:
            return []
        if not isinstance(stop, (str, list)):
            raise ValueError("stop must be a string or array of strings")
        stops = [stop] if isinstance(stop, str) else stop
        return [s for s in stops if isinstance(s, str) and s][:4]

    @staticmethod
    def _earliest_stop(text: str, stops: List[str]) -> int:
        """Index of the earliest stop-string match, or -1."""
        return min((i for i in (text.find(s) for s in stops) if i >= 0),
                   default=-1)

    @staticmethod
    def _tokens_until(tok, tokens: List[int], cut: int) -> int:
        """How many leading tokens produce the first ``cut`` chars of the
        decoded text (the token crossing the boundary is included)."""
        if cut <= 0:
            return 0
        detok = IncrementalDetokenizer(tok)
        total = 0
        for i, t in enumerate(tokens):
            total += len(detok.feed([t]))
            if total >= cut:
                return i + 1
        return len(tokens)

    @staticmethod
    def _sampling_fields(body: dict) -> dict:
        """OpenAI body → wire sampling fields (top_k / min_p /
        repetition_penalty are the usual engine extensions)."""
        out = {
            "temperature": float(body.get("temperature", 0.0)),
            "top_k": int(body.get("top_k", 0)),
            "top_p": float(body.get("top_p", 1.0)),
            "min_p": float(body.get("min_p", 0.0)),
            "repetition_penalty": float(body.get("repetition_penalty", 1.0)),
            "presence_penalty": float(body.get("presence_penalty", 0.0)),
            "frequency_penalty": float(body.get("frequency_penalty", 0.0)),
        }
        if body.get("seed") is not None:
            out["seed"] = int(body["seed"])
        if body.get("logprobs"):
            out["logprobs"] = True
        if body.get("lora"):
            out["lora"] = str(body["lora"])
        # Regex-constrained output (sglang `regex` / vLLM `guided_regex`).
        # `is not None`: "" is a legal pattern (empty output only).
        regex = body.get("regex")
        if regex is None:
            regex = body.get("guided_regex")
        if regex is not None:
            out["regex"] = str(regex)
        # Schema-constrained output (vLLM `guided_json`).
        gj = body.get("guided_json")
        if gj is not None:
            if not isinstance(gj, dict):
                raise ValueError("guided_json must be a JSON Schema object")
            out["json_schema"] = gj
        rf = body.get("response_format")
        if rf is not None:
            rft = rf.get("type") if isinstance(rf, dict) else None
            if rft == "json_object":
                out["json_mode"] = True
            elif rft == "json_schema":
                # OpenAI structured outputs: response_format.json_schema
                # .schema carries the schema itself.
                js = rf.get("json_schema")
                schema = js.get("schema") if isinstance(js, dict) else None
                if not isinstance(schema, dict):
                    raise ValueError(
                        "response_format.json_schema.schema must be a "
                        "JSON Schema object")
                out["json_schema"] = schema
            elif rft != "text":
                # Silently ignoring an unsupported constraint would return
                # unconstrained output a client will feed to json.loads.
                raise ValueError(
                    f"unsupported response_format {rft!r} (supported: "
                    "text, json_object, json_schema)")
        return out

    @staticmethod
    def _logprobs_obj(chat: bool, text_tokens: List[str],
                      lps: List[float]) -> Optional[dict]:
        if not lps:
            return None
        if chat:
            return {"content": [{"token": t, "logprob": l}
                                for t, l in zip(text_tokens, lps)]}
        return {"tokens": text_tokens, "token_logprobs": lps,
                "top_logprobs": None, "text_offset": None}

    def _complete(self, st: _State, body: dict, prompt_text: str, chat: bool):
        tok = st.tokenizer
        # No BOS: byte-fallback ids must stay inside small demo vocabs; HF
        # tokenizers add specials via their own template when configured.
        ids = tok.encode(prompt_text, add_bos=False)
        try:
            # Validate edge-side: a caller mistake must be a 400, not the
            # backend's wire error surfacing as a 502 (which retry
            # middleware would pointlessly retry). The field conversions
            # themselves can raise too ("temperature": "hot") — they
            # belong inside this guard as much as from_wire does.
            req = {
                "op": "generate",
                "prompt": ids,
                "max_new_tokens": int(body.get("max_tokens")
                                      or st.default_max_tokens),
                **self._sampling_fields(body),
            }
            if tok.eos_id is not None:
                req["stop_token"] = tok.eos_id
            # End-to-end deadline (extension field): rides the wire as
            # timeout_s; the router stamps the absolute deadline from it
            # and every hop downstream spends from that one budget.
            t = body.get("timeout_s", body.get("timeout"))
            if t is not None:
                t = float(t)
                if not 0 < t <= MAX_TIMEOUT_S:
                    raise ValueError(
                        f"timeout_s must be in (0, {MAX_TIMEOUT_S:g}]")
                req["timeout_s"] = t
            SamplingParams.from_wire(req)
            stops = self._parse_stops(body)
        except (ValueError, TypeError) as e:
            return self._error(400, f"invalid sampling parameters: {e}")
        rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        created = int(time.time())
        if body.get("stream"):
            return self._stream(st, req, rid, created, chat, stops)
        try:
            # Transport timeout shadows the end-to-end budget (+5 s grace
            # for the backend's own structured deadline reply to arrive).
            resp, _, _ = request_once(st.backend, st.backend_req(req),
                                      timeout=(req["timeout_s"] + 5
                                               if "timeout_s" in req
                                               else 300))
        except OSError as e:
            return self._error(502, f"backend: {e}", "server_error")
        if resp is None or "error" in (resp or {}):
            return self._backend_error(resp)
        tokens = resp.get("tokens", [])
        lps = resp.get("logprobs", [])
        text = tok.decode(tokens)
        finish = ("stop" if (tok.eos_id is not None and tokens
                             and tokens[-1] == tok.eos_id) else "length")
        if stops:
            cut = self._earliest_stop(text, stops)
            if cut >= 0:
                # Truncate tokens/logprobs/usage with the text — the client
                # only ever sees the kept prefix (the backend generated
                # more; stop matching is this edge's concern).
                keep = self._tokens_until(tok, tokens, cut)
                tokens, lps = tokens[:keep], lps[:keep]
                text, finish = text[:cut], "stop"
        usage = {"prompt_tokens": len(ids), "completion_tokens": len(tokens),
                 "total_tokens": len(ids) + len(tokens)}
        lp_obj = (self._logprobs_obj(chat, [tok.decode([t]) for t in tokens],
                                     lps) if lps else None)
        if chat:
            choice = {"index": 0, "finish_reason": finish,
                      "message": {"role": "assistant", "content": text}}
            if lp_obj is not None:
                choice["logprobs"] = lp_obj
            return self._json(200, {
                "id": rid, "object": "chat.completion", "created": created,
                "model": st.model, "usage": usage, "choices": [choice]})
        return self._json(200, {
            "id": rid, "object": "text_completion", "created": created,
            "model": st.model, "usage": usage,
            "choices": [{"index": 0, "text": text, "logprobs": lp_obj,
                         "finish_reason": finish}]})

    def _sse(self, obj) -> None:
        data = b"data: " + json.dumps(obj).encode() + b"\n\n" \
            if obj != "[DONE]" else b"data: [DONE]\n\n"
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _sse_comment(self, text: str = "keep-alive") -> None:
        """SSE comment frame (``: ...``): ignored by every SSE parser,
        but its WRITE fails fast when the client is gone and its ARRIVAL
        tells a waiting client the path is alive — pure liveness, never
        part of the completion payload."""
        data = f": {text}\n\n".encode()
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _chunk(self, st, rid, created, chat, text: Optional[str],
               finish: Optional[str], lp_obj: Optional[dict] = None) -> dict:
        if chat:
            delta = {} if text is None else {"content": text}
            choice = {"index": 0, "delta": delta, "finish_reason": finish}
            if lp_obj is not None:
                choice["logprobs"] = lp_obj
            return {"id": rid, "object": "chat.completion.chunk",
                    "created": created, "model": st.model,
                    "choices": [choice]}
        return {"id": rid, "object": "text_completion", "created": created,
                "model": st.model,
                "choices": [{"index": 0, "text": text or "",
                             "logprobs": lp_obj, "finish_reason": finish}]}

    def _stream(self, st: _State, req: dict, rid: str, created: int,
                chat: bool, stops: List[str] = ()):
        req["stream"] = True
        detok = IncrementalDetokenizer(st.tokenizer)
        # Stop-string hold-back: never emit the last len(longest stop)-1
        # chars until more text rules out a partial stop match.
        holdback = max((len(s) for s in stops), default=1) - 1
        buf = ""
        host, port = st.backend.rsplit(":", 1)
        try:
            conn = socket.create_connection((host, int(port)), timeout=300)
        except OSError as e:
            return self._error(502, f"backend: {e}", "server_error")
        # First frame BEFORE committing to SSE: an admission-time rejection
        # (overloaded / draining / spent deadline) must surface as a real
        # HTTP status + Retry-After — retry middleware and load balancers
        # can't see codes buried inside a 200 event stream.
        try:
            send_msg(conn, st.backend_req(req))
            first_frame, _, _ = recv_msg(conn)
        except OSError as e:
            conn.close()
            return self._error(502, f"backend: {e}", "server_error")
        if first_frame is None:
            conn.close()
            return self._error(502, "backend closed before streaming",
                               "server_error")
        if "error" in first_frame:
            conn.close()
            return self._backend_error(first_frame)
        self._status = 200
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        rid_hdr = getattr(self, "_request_id", None)
        if rid_hdr:
            self.send_header("X-Request-Id", rid_hdr)
        self.end_headers()
        if chat:
            first = self._chunk(st, rid, created, chat, None, None)
            first["choices"][0]["delta"] = {"role": "assistant"}
            self._sse(first)
        finish, stopped = "length", False
        chars_out = 0                       # text chars emitted to the client
        want_lp = bool(req.get("logprobs"))
        # With stop strings, per-frame logprob chunks could cover tokens the
        # stop later cuts (text lags tokens through the hold-back buffer) —
        # defer to ONE exact chunk truncated against the emitted text.
        defer_lp = want_lp and bool(stops)
        all_toks: List[int] = []
        all_lps: List[Optional[float]] = []

        def send_text(text: str) -> None:
            nonlocal chars_out
            chars_out += len(text)
            self._sse(self._chunk(st, rid, created, chat, text, None))

        def emit_text(delta: str) -> bool:
            """Emit delta through the stop-string buffer; True = stop hit
            (buffer already flushed up to the match)."""
            nonlocal buf, finish
            if not stops:
                if delta:
                    send_text(delta)
                return False
            buf += delta
            cut = self._earliest_stop(buf, stops)
            if cut >= 0:
                if buf[:cut]:
                    send_text(buf[:cut])
                buf, finish = "", "stop"
                return True
            safe = buf[:-holdback] if holdback else buf
            if safe:
                send_text(safe)
                buf = buf[len(safe):]
            return False

        # Idle-liveness plumbing: the per-recv timeout becomes the
        # keep-alive period; each expiry emits ONE comment frame and
        # re-arms, bounded by the original 300 s true-idle cap (a hung
        # backend must still die, keepalives notwithstanding). Deadline
        # budgets are untouched — the stamp rode the FIRST request and
        # comment frames never re-arm anything downstream.
        ka_s = st.sse_keepalive_s
        idle_cap = 300.0
        last_progress = time.monotonic()
        if ka_s > 0:
            conn.settimeout(ka_s)
        try:
            with conn:
                while True:
                    if first_frame is not None:
                        frame, first_frame = first_frame, None
                    else:
                        try:
                            frame, _, _ = recv_msg(conn)
                        except socket.timeout:
                            if time.monotonic() - last_progress > idle_cap:
                                break
                            self._sse_comment()
                            continue
                    if frame is None:
                        break
                    last_progress = time.monotonic()
                    if frame.get("keepalive"):
                        # Router-forwarded liveness (a backend hop is
                        # slow, not dead): surface as a comment frame —
                        # never a token chunk.
                        self._sse_comment()
                        continue
                    if "error" in frame:
                        self._sse(self._chunk(st, rid, created, chat,
                                              f"\n[error: {frame['error']}]",
                                              "stop"))
                        break
                    toks = frame.get("tokens", [])
                    if toks:
                        if (st.tokenizer.eos_id is not None
                                and toks[-1] == st.tokenizer.eos_id):
                            finish = "stop"
                        if defer_lp:
                            all_toks.extend(toks)
                            all_lps.extend(frame.get("logprobs")
                                           or [None] * len(toks))
                        hit = emit_text(detok.feed(toks))
                        if (not hit and want_lp and not defer_lp
                                and frame.get("logprobs")):
                            # Token-level logprobs ride their own chunk —
                            # text deltas lag tokens (detok buffering), so
                            # aligning them to text chunks would
                            # misattribute positions.
                            lp_obj = self._logprobs_obj(
                                chat,
                                [st.tokenizer.decode([t]) for t in toks],
                                frame["logprobs"])
                            if lp_obj is not None:
                                self._sse(self._chunk(st, rid, created, chat,
                                                      None, None, lp_obj))
                        if hit:
                            stopped = True
                            break  # client-side cut; backend stream abandoned
                    if frame.get("done"):
                        break
            if not stopped:
                tail = detok.flush()
                if stops:
                    buf += tail
                    cut = self._earliest_stop(buf, stops)
                    if cut >= 0:
                        buf, finish = buf[:cut], "stop"
                    if buf:
                        send_text(buf)
                elif tail:
                    send_text(tail)
            if defer_lp and all_toks:
                # Exactly the tokens whose text was emitted — mirrors the
                # non-stream truncation contract.
                keep = self._tokens_until(st.tokenizer, all_toks, chars_out)
                lp_obj = self._logprobs_obj(
                    chat, [st.tokenizer.decode([t]) for t in all_toks[:keep]],
                    all_lps[:keep])
                if lp_obj is not None:
                    self._sse(self._chunk(st, rid, created, chat, None, None,
                                          lp_obj))
            self._sse(self._chunk(st, rid, created, chat, None, finish))
            self._sse("[DONE]")
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass


class FrontendServer(ThreadingHTTPServer):
    daemon_threads = True


def serve(args) -> FrontendServer:
    server = FrontendServer((args.host, args.port), Handler)
    server.state = _State(args)
    return server


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("rbg-tpu OpenAI-compatible front end")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("RBG_HTTP_PORT", "8000")))
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--backend",
                    default=os.environ.get("RBG_ROUTER_ADDR",
                                           "127.0.0.1:9100"),
                    help="router (or unified engine server) host:port")
    ap.add_argument("--model", default=os.environ.get("RBG_MODEL", "tiny"))
    ap.add_argument("--tokenizer-path",
                    default=os.environ.get("RBG_TOKENIZER_PATH", ""))
    ap.add_argument("--default-max-tokens", type=int, default=64)
    ap.add_argument("--sse-keepalive-s", type=float, default=15.0,
                    help="emit an SSE comment frame after this many idle "
                         "seconds on a live stream so clients detect dead "
                         "hops fast (0 disables)")
    args = ap.parse_args(argv)
    server = serve(args)
    print(f"http frontend on {args.host}:{args.port} -> {args.backend}",
          flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
