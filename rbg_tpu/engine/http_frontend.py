"""OpenAI-compatible HTTP front end with SSE streaming.

Reference context: the reference's serving examples assume engines speak
HTTP (``examples/inference/pd-disagg-leader-worker.yaml`` router args
``http://...:8000``); VERDICT r3 missing #7. This process is the public
edge of a serving group:

    client ──HTTP/SSE──> http_frontend ──TCP──> router ──> prefill/decode

Endpoints:

* ``POST /v1/completions``       — OpenAI Completions (+``stream``)
* ``POST /v1/chat/completions``  — OpenAI Chat (+``stream``)
* ``GET  /v1/models``            — the served model
* ``GET  /healthz``              — liveness + backend reachability

Tokenization lives HERE (encode prompts, incrementally detokenize streamed
ids — ``tokenizer.IncrementalDetokenizer``); the internal TCP protocol
stays token-id based (PD transfer unchanged)."""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg
from rbg_tpu.engine.tokenizer import IncrementalDetokenizer, load_tokenizer


def _chat_to_prompt(messages: List[dict]) -> str:
    """Minimal chat template: role-tagged lines + assistant cue. Real
    deployments pass --tokenizer-path whose chat template could be applied;
    byte-level serving uses this plain form."""
    lines = [f"{m.get('role', 'user')}: {m.get('content', '')}"
             for m in messages]
    lines.append("assistant:")
    return "\n".join(lines)


class _State:
    def __init__(self, args):
        self.backend = args.backend
        self.model = args.model
        self.tokenizer = load_tokenizer(args.tokenizer_path or None)
        self.default_max_tokens = args.default_max_tokens


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "rbg-tpu"

    def log_message(self, *a):
        pass

    # ---- plumbing ----

    def _json(self, code: int, body: dict):
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, message: str, etype: str = "invalid_request_error"):
        self._json(code, {"error": {"message": message, "type": etype}})

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw)

    # ---- routes ----

    def do_GET(self):
        st: _State = self.server.state
        if self.path == "/healthz":
            ok = True
            try:
                h, _, _ = request_once(st.backend, {"op": "health"}, timeout=5)
                ok = bool(h and (h.get("ok") or "pd" in h))
            except OSError:
                ok = False
            return self._json(200 if ok else 503,
                              {"ok": ok, "backend": st.backend})
        if self.path == "/v1/models":
            return self._json(200, {"object": "list", "data": [
                {"id": st.model, "object": "model", "owned_by": "rbg-tpu"}]})
        return self._error(404, f"no route {self.path}")

    def do_POST(self):
        st: _State = self.server.state
        try:
            body = self._body()
        except json.JSONDecodeError as e:
            return self._error(400, f"bad JSON: {e}")
        if self.path == "/v1/completions":
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = "".join(prompt)
            return self._complete(st, body, prompt, chat=False)
        if self.path == "/v1/chat/completions":
            messages = body.get("messages") or []
            return self._complete(st, body, _chat_to_prompt(messages),
                                  chat=True)
        return self._error(404, f"no route {self.path}")

    # ---- completion core ----

    def _complete(self, st: _State, body: dict, prompt_text: str, chat: bool):
        tok = st.tokenizer
        # No BOS: byte-fallback ids must stay inside small demo vocabs; HF
        # tokenizers add specials via their own template when configured.
        ids = tok.encode(prompt_text, add_bos=False)
        req = {
            "op": "generate",
            "prompt": ids,
            "max_new_tokens": int(body.get("max_tokens")
                                  or st.default_max_tokens),
            "temperature": float(body.get("temperature", 0.0)),
            "top_k": int(body.get("top_k", 0)),
        }
        if tok.eos_id is not None:
            req["stop_token"] = tok.eos_id
        rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        created = int(time.time())
        if body.get("stream"):
            return self._stream(st, req, rid, created, chat, len(ids))
        try:
            resp, _, _ = request_once(st.backend, req, timeout=300)
        except OSError as e:
            return self._error(502, f"backend: {e}", "server_error")
        if resp is None or "error" in (resp or {}):
            return self._error(502, (resp or {}).get("error", "no response"),
                               "server_error")
        tokens = resp.get("tokens", [])
        text = tok.decode(tokens)
        finish = ("stop" if (tok.eos_id is not None and tokens
                             and tokens[-1] == tok.eos_id) else "length")
        usage = {"prompt_tokens": len(ids), "completion_tokens": len(tokens),
                 "total_tokens": len(ids) + len(tokens)}
        if chat:
            return self._json(200, {
                "id": rid, "object": "chat.completion", "created": created,
                "model": st.model, "usage": usage,
                "choices": [{"index": 0, "finish_reason": finish,
                             "message": {"role": "assistant",
                                         "content": text}}]})
        return self._json(200, {
            "id": rid, "object": "text_completion", "created": created,
            "model": st.model, "usage": usage,
            "choices": [{"index": 0, "text": text, "logprobs": None,
                         "finish_reason": finish}]})

    def _sse(self, obj) -> None:
        data = b"data: " + json.dumps(obj).encode() + b"\n\n" \
            if obj != "[DONE]" else b"data: [DONE]\n\n"
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _chunk(self, st, rid, created, chat, text: Optional[str],
               finish: Optional[str]) -> dict:
        if chat:
            delta = {} if text is None else {"content": text}
            return {"id": rid, "object": "chat.completion.chunk",
                    "created": created, "model": st.model,
                    "choices": [{"index": 0, "delta": delta,
                                 "finish_reason": finish}]}
        return {"id": rid, "object": "text_completion", "created": created,
                "model": st.model,
                "choices": [{"index": 0, "text": text or "",
                             "logprobs": None, "finish_reason": finish}]}

    def _stream(self, st: _State, req: dict, rid: str, created: int,
                chat: bool, n_prompt: int):
        req["stream"] = True
        detok = IncrementalDetokenizer(st.tokenizer)
        host, port = st.backend.rsplit(":", 1)
        try:
            conn = socket.create_connection((host, int(port)), timeout=300)
        except OSError as e:
            return self._error(502, f"backend: {e}", "server_error")
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        if chat:
            first = self._chunk(st, rid, created, chat, None, None)
            first["choices"][0]["delta"] = {"role": "assistant"}
            self._sse(first)
        n_tokens, finish = 0, "length"
        try:
            with conn:
                send_msg(conn, req)
                while True:
                    frame, _, _ = recv_msg(conn)
                    if frame is None:
                        break
                    if "error" in frame:
                        self._sse(self._chunk(st, rid, created, chat,
                                              f"\n[error: {frame['error']}]",
                                              "stop"))
                        break
                    toks = frame.get("tokens", [])
                    if toks:
                        n_tokens += len(toks)
                        if (st.tokenizer.eos_id is not None
                                and toks[-1] == st.tokenizer.eos_id):
                            finish = "stop"
                        delta = detok.feed(toks)
                        if delta:
                            self._sse(self._chunk(st, rid, created, chat,
                                                  delta, None))
                    if frame.get("done"):
                        break
            tail = detok.flush()
            if tail:
                self._sse(self._chunk(st, rid, created, chat, tail, None))
            self._sse(self._chunk(st, rid, created, chat, None, finish))
            self._sse("[DONE]")
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass


class FrontendServer(ThreadingHTTPServer):
    daemon_threads = True


def serve(args) -> FrontendServer:
    server = FrontendServer((args.host, args.port), Handler)
    server.state = _State(args)
    return server


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("rbg-tpu OpenAI-compatible front end")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("RBG_HTTP_PORT", "8000")))
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--backend",
                    default=os.environ.get("RBG_ROUTER_ADDR",
                                           "127.0.0.1:9100"),
                    help="router (or unified engine server) host:port")
    ap.add_argument("--model", default=os.environ.get("RBG_MODEL", "tiny"))
    ap.add_argument("--tokenizer-path",
                    default=os.environ.get("RBG_TOKENIZER_PATH", ""))
    ap.add_argument("--default-max-tokens", type=int, default=64)
    args = ap.parse_args(argv)
    server = serve(args)
    print(f"http frontend on {args.host}:{args.port} -> {args.backend}",
          flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
