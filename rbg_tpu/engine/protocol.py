"""Wire protocol for engine/router processes.

Newline-delimited JSON headers over TCP, with optional raw binary KV payload
(lengths declared in the header — no base64 tax on multi-MB KV bundles).
This is the DCN path of the PD-disagg KV transfer; within a slice the
in-process PDPair path (device gather/scatter) is used instead.

Ops:
  {"op": "health"}                              → {"ok": true, "mode": ...}
  {"op": "generate", "prompt": [...], ...}      → {"tokens": [...], "ttft_s": x}
  {"op": "prefill", "prompt": [...], ...}       → bundle header + K/V bytes
  {"op": "decode_bundle", ...hdr} + K/V bytes   → {"tokens": [...]}
"""

from __future__ import annotations

import json
import socket
from typing import Optional, Tuple

import numpy as np

# Structured rejection codes riding the wire alongside "error". The edge
# maps them to HTTP (429 / 503 / 504), the router routes around the
# retryable ones (a shed or draining backend is HEALTHY — never evicted),
# and every layer increments its own counter. Plain-string "error" replies
# without a code stay what they always were: application errors.
# Canonical catalog: rbg_tpu/api/errors.py (the error-code-registry lint
# rule enforces it); re-exported here because the server process imports
# protocol.py before jax loads and callers already import from here.
from rbg_tpu.api.errors import (CODE_DEADLINE, CODE_DRAINING,  # noqa: F401
                                CODE_KV_STREAM, CODE_OVERLOADED,
                                CODE_REJECTED, RETRYABLE_REJECT_CODES)


class Rejected(RuntimeError):
    """Structured service rejection. ``code`` rides the wire so the edge
    can map it (429 / 503 / 504) and the router can route around it;
    ``retry_after_s`` is the backpressure hint for shed replies. Lives
    HERE (not in service.py) so the server process can import it without
    pulling jax before the port binds."""

    code = CODE_REJECTED

    def __init__(self, msg: str, retry_after_s=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s

    def to_wire(self) -> dict:
        frame = {"error": str(self), "code": self.code}
        if self.retry_after_s is not None:
            frame["retry_after_s"] = round(self.retry_after_s, 3)
        return frame


class Overloaded(Rejected):
    code = CODE_OVERLOADED


class DeadlineExceeded(Rejected):
    code = CODE_DEADLINE


def send_msg(sock: socket.socket, obj: dict,
             k_bytes: Optional[bytes] = None,
             v_bytes: Optional[bytes] = None) -> None:
    obj = dict(obj)
    if k_bytes is not None:
        obj["bin_k"] = len(k_bytes)
        obj["bin_v"] = len(v_bytes or b"")
    sock.sendall(json.dumps(obj).encode() + b"\n")
    if k_bytes is not None:
        sock.sendall(k_bytes)
        if v_bytes:
            sock.sendall(v_bytes)


_rbufs: "weakref.WeakKeyDictionary" = None  # initialized below


def _rbuf(sock: socket.socket) -> bytearray:
    """Per-socket receive buffer (persists across messages — bytes of the
    NEXT message read in one recv must not be swallowed). Deliberately NOT
    ``sock.makefile()``: a makefile reader pins the socket's fd open past
    ``close()`` (socket._io_refs) and, stored in a weak map keyed by the
    socket it references, would keep the entry — and the connection —
    alive forever. A plain bytearray has no back-reference, so the entry
    dies with the socket and ``close()`` really closes."""
    global _rbufs
    if _rbufs is None:
        import weakref
        _rbufs = weakref.WeakKeyDictionary()
    buf = _rbufs.get(sock)
    if buf is None:
        buf = bytearray()
        _rbufs[sock] = buf
    return buf


_RECV_CHUNK = 1 << 16


def _read_line(sock: socket.socket, buf: bytearray) -> bytes:
    while True:
        nl = buf.find(b"\n")
        if nl >= 0:
            line = bytes(buf[:nl + 1])
            del buf[:nl + 1]
            return line
        chunk = sock.recv(_RECV_CHUNK)
        if not chunk:
            if buf:
                raise ConnectionError("peer closed mid-header")
            return b""
        buf.extend(chunk)


def _read_exact(sock: socket.socket, buf: bytearray, n: int) -> bytes:
    while len(buf) < n:
        chunk = sock.recv(_RECV_CHUNK)
        if not chunk:
            raise ConnectionError("peer closed mid-payload")
        buf.extend(chunk)
    out = bytes(buf[:n])
    del buf[:n]
    return out


def recv_msg(sock: socket.socket) -> Tuple[Optional[dict], Optional[bytes], Optional[bytes]]:
    buf = _rbuf(sock)
    line = _read_line(sock, buf)
    if not line:
        return None, None, None
    obj = json.loads(line)
    k = v = None
    if "bin_k" in obj:
        k = _read_exact(sock, buf, obj["bin_k"])
        v = _read_exact(sock, buf, obj.get("bin_v", 0))
    return obj, k, v


def token_ok(presented, expected) -> bool:
    """Constant-time bearer-token compare for the data-plane gates
    (engine server / router / kv pool). Compares utf-8 BYTES:
    ``hmac.compare_digest`` raises TypeError on non-ASCII str operands
    (admin.py documents the same pitfall)."""
    import hmac
    return hmac.compare_digest(str(presented or "").encode("utf-8"),
                               str(expected or "").encode("utf-8"))


def bundle_to_wire(bundle) -> Tuple[dict, bytes, bytes]:
    header = {
        "prompt": bundle.prompt,
        "first_token": bundle.first_token,
        "shape": list(bundle.k_data.shape),
        "dtype": str(bundle.k_data.dtype),
    }
    return header, bundle.k_data.tobytes(), bundle.v_data.tobytes()


def bundle_from_wire(header: dict, k_bytes: bytes, v_bytes: bytes):
    from rbg_tpu.engine.pd import KVBundle

    shape = tuple(header["shape"])
    dtype = np.dtype(header["dtype"])
    return KVBundle(
        prompt=list(header["prompt"]),
        first_token=int(header["first_token"]),
        k_data=np.frombuffer(k_bytes, dtype).reshape(shape),
        v_data=np.frombuffer(v_bytes, dtype).reshape(shape),
    )


def request_once(addr: str, obj: dict, k_bytes=None, v_bytes=None,
                 timeout: float = 120.0, ssl_context=None):
    """One request/response round trip to ``host:port`` (optionally TLS —
    the admin wire with a cert dir configured)."""
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=timeout) as raw:
        if ssl_context is not None:
            with ssl_context.wrap_socket(raw, server_hostname=host) as s:
                send_msg(s, obj, k_bytes, v_bytes)
                return recv_msg(s)
        send_msg(raw, obj, k_bytes, v_bytes)
        return recv_msg(raw)
