"""Wire protocol for engine/router processes.

Newline-delimited JSON headers over TCP, with optional raw binary KV payload
(lengths declared in the header — no base64 tax on multi-MB KV bundles).
This is the DCN path of the PD-disagg KV transfer; within a slice the
in-process PDPair path (device gather/scatter) is used instead.

Ops:
  {"op": "health"}                              → {"ok": true, "mode": ...}
  {"op": "generate", "prompt": [...], ...}      → {"tokens": [...], "ttft_s": x}
  {"op": "prefill", "prompt": [...], ...}       → bundle header + K/V bytes
  {"op": "decode_bundle", ...hdr} + K/V bytes   → {"tokens": [...]}
"""

from __future__ import annotations

import json
import socket
from typing import Optional, Tuple

import numpy as np


def send_msg(sock: socket.socket, obj: dict,
             k_bytes: Optional[bytes] = None,
             v_bytes: Optional[bytes] = None) -> None:
    obj = dict(obj)
    if k_bytes is not None:
        obj["bin_k"] = len(k_bytes)
        obj["bin_v"] = len(v_bytes or b"")
    sock.sendall(json.dumps(obj).encode() + b"\n")
    if k_bytes is not None:
        sock.sendall(k_bytes)
        if v_bytes:
            sock.sendall(v_bytes)


_rfiles: "weakref.WeakKeyDictionary" = None  # initialized below


def _rfile(sock: socket.socket):
    """Per-socket buffered reader (persists across messages — a fresh
    makefile per call would swallow buffered bytes of the next message).
    socket.socket has __slots__, so the association lives in a weak map."""
    global _rfiles
    if _rfiles is None:
        import weakref
        _rfiles = weakref.WeakKeyDictionary()
    f = _rfiles.get(sock)
    if f is None:
        f = sock.makefile("rb", buffering=1 << 16)
        _rfiles[sock] = f
    return f


def _read_exact(f, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-payload")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Tuple[Optional[dict], Optional[bytes], Optional[bytes]]:
    f = _rfile(sock)
    line = f.readline()
    if not line:
        return None, None, None
    obj = json.loads(line)
    k = v = None
    if "bin_k" in obj:
        k = _read_exact(f, obj["bin_k"])
        v = _read_exact(f, obj.get("bin_v", 0))
    return obj, k, v


def bundle_to_wire(bundle) -> Tuple[dict, bytes, bytes]:
    header = {
        "prompt": bundle.prompt,
        "first_token": bundle.first_token,
        "shape": list(bundle.k_data.shape),
        "dtype": str(bundle.k_data.dtype),
    }
    return header, bundle.k_data.tobytes(), bundle.v_data.tobytes()


def bundle_from_wire(header: dict, k_bytes: bytes, v_bytes: bytes):
    from rbg_tpu.engine.pd import KVBundle

    shape = tuple(header["shape"])
    dtype = np.dtype(header["dtype"])
    return KVBundle(
        prompt=list(header["prompt"]),
        first_token=int(header["first_token"]),
        k_data=np.frombuffer(k_bytes, dtype).reshape(shape),
        v_data=np.frombuffer(v_bytes, dtype).reshape(shape),
    )


def request_once(addr: str, obj: dict, k_bytes=None, v_bytes=None,
                 timeout: float = 120.0, ssl_context=None):
    """One request/response round trip to ``host:port`` (optionally TLS —
    the admin wire with a cert dir configured)."""
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=timeout) as raw:
        if ssl_context is not None:
            with ssl_context.wrap_socket(raw, server_hostname=host) as s:
                send_msg(s, obj, k_bytes, v_bytes)
                return recv_msg(s)
        send_msg(raw, obj, k_bytes, v_bytes)
        return recv_msg(raw)
