"""EngineService: background continuous-batching loop + blocking submit API.

Requests arriving on different connections batch together on the device —
the server threads only enqueue and wait; one loop thread owns the engine
(single-writer, no engine locking on the hot path).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.engine import Engine


class _Pending:
    __slots__ = ("tokens", "done", "t_submit", "t_first", "error")

    def __init__(self):
        self.tokens: List[int] = []
        self.done = threading.Event()
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.error: Optional[str] = None


DEFAULT_TIMEOUT_S = 600.0


class EngineService:
    def __init__(self, cfg: EngineConfig, params=None, mesh=None):
        self.engine = Engine(cfg, params=params, mesh=mesh)
        self._pending: Dict[int, _Pending] = {}
        self._lock = threading.Lock()          # guards queue handoff only
        self._wake = threading.Event()
        self._stop = False
        self._queue: List[Tuple[List[int], SamplingParams, _Pending]] = []
        self._cancels: List[_Pending] = []
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="engine-loop")
        self._thread.start()

    def submit(self, prompt: List[int], sampling: SamplingParams,
               timeout: float = DEFAULT_TIMEOUT_S) -> Tuple[List[int], float]:
        """Blocking generate. Returns (tokens, ttft_seconds)."""
        p = self.submit_async(prompt, sampling)
        if not p.done.wait(timeout):
            self.cancel(p)  # recycle batch slot + KV pages, don't orphan
            raise TimeoutError("generation timed out")
        if p.error:
            raise ValueError(p.error)
        return p.tokens, (p.t_first - p.t_submit if p.t_first else 0.0)

    def cancel(self, pending: "_Pending") -> None:
        """Abort an in-flight request (routed through the loop thread)."""
        with self._lock:
            self._cancels.append(pending)
        self._wake.set()

    def submit_async(self, prompt: List[int], sampling: SamplingParams) -> _Pending:
        """Enqueue and return the live Pending (stream by watching .tokens
        grow until .done is set)."""
        p = _Pending()
        with self._lock:
            self._queue.append((prompt, sampling, p))
        self._wake.set()
        return p

    def stats(self) -> dict:
        out = dict(self.engine.metrics)
        out["running"] = len(self.engine.running)
        out["waiting"] = len(self.engine.waiting)
        out["free_pages"] = self.engine.allocator.free_pages
        out["radix_nodes"] = (self.engine.radix.num_nodes
                              if self.engine.radix is not None else 0)
        return out

    def stop(self):
        self._stop = True
        self._wake.set()

    def _loop(self):
        eng = self.engine
        while not self._stop:
            with self._lock:
                newly = self._queue
                self._queue = []
                cancels = self._cancels
                self._cancels = []
            for prompt, sampling, pending in newly:
                try:
                    rid = eng.add_request(prompt, sampling)
                except Exception as e:
                    # A bad request must fail ITSELF, never the loop thread.
                    pending.error = str(e)
                    pending.done.set()
                    continue
                self._pending[rid] = pending
            for pending in cancels:
                rid = next((r for r, p in self._pending.items() if p is pending),
                           None)
                if rid is not None:
                    eng.cancel_request(rid)
                    del self._pending[rid]
                    pending.done.set()
            if not eng.has_work():
                self._wake.wait(0.01)
                self._wake.clear()
                continue
            for ev in eng.step():
                pending = self._pending.get(ev.request_id)
                if pending is None:
                    continue
                if pending.t_first is None:
                    pending.t_first = time.perf_counter()
                pending.tokens.append(ev.token)
                if ev.finished:
                    pending.done.set()
                    del self._pending[ev.request_id]
