"""Engine services: background continuous-batching loops + blocking APIs.

Requests arriving on different connections batch together on the device —
server threads only enqueue and wait; ONE loop thread owns each engine
(single-writer, no engine locking on the hot path). ``EngineService`` serves
unified generate; ``DecodeService`` serves the disaggregated decode role
(KV-bundle injection). Both share the same loop machinery: locked queue
swap, admission capped at the engine's max_batch, cancel routing (timeouts
recycle batch slots + KV pages), and the event pump.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.engine import Engine
# Re-exported here for callers that think in service terms; defined in
# protocol.py so jax-free processes (server startup) can import them.
from rbg_tpu.engine.protocol import (CODE_DEADLINE, DeadlineExceeded,
                                     Overloaded, Rejected)
from rbg_tpu.obs import names, trace
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.obs.slo import SLOTargets, SLOTracker
from rbg_tpu.utils import jitwatch
from rbg_tpu.utils.locktrace import named_lock
from rbg_tpu.utils.racetrace import guard as _race_guard


class _Pending:
    __slots__ = ("tokens", "logprobs", "done", "t_submit", "t_first", "error",
                 "code", "deadline", "span_parent", "span_queue", "span_scan",
                 "stream_rx")

    def __init__(self, deadline: Optional[float] = None):
        self.tokens: List[int] = []
        self.logprobs: List[float] = []   # 1:1 with tokens when requested
        self.done = threading.Event()
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.error: Optional[str] = None
        self.code: Optional[str] = None   # structured rejection code
        self.deadline = deadline          # absolute time.monotonic() budget
        # Tracing (obs/trace.py): parent span of this request plus the
        # queue-wait / scan child spans — NULL_SPAN when unsampled, so
        # every lifecycle site below ends them unconditionally.
        self.span_parent = trace.NULL_SPAN
        self.span_queue = trace.NULL_SPAN
        self.span_scan = trace.NULL_SPAN
        # KV stream receiver backing this request (decode_stream path) —
        # its t_first_step is stamped at the first decode token, the
        # kv_stream_overlap invariant's input.
        self.stream_rx = None


DEFAULT_TIMEOUT_S = 600.0
# Completion timestamps kept for the estimated-wait admission gate.
_RATE_WINDOW = 64
# Prefill-throughput EMA expiry for the early-reject predictor: past
# this, the rate is absence-of-signal, not a measurement (a shed does
# no prefill, so a stale-slow rate could otherwise never re-learn).
_PF_RATE_TTL_S = 30.0
# Fallback backpressure hint when no throughput estimate exists yet.
_RETRY_AFTER_FLOOR_S = 0.5


def embed_prompts(engine: Engine, prompts: List[List[int]]) -> List[List[float]]:
    """Mean-pooled final-norm hidden states, ONE batched forward for the
    whole list (encode_hidden is [B, T]-shaped; a per-string forward would
    cost B serial dispatches). Pads (B, T) to (chunk-multiple) buckets and
    caches one jitted program per bucket on the engine. Safe to call from
    server threads — reads engine.params only (jit dispatch is
    thread-safe; no queue state is touched)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    for p in prompts:
        engine._check_prompt(p)
        if len(p) > engine.cfg.max_seq_len:
            raise ValueError(f"prompt ({len(p)} tokens) exceeds "
                             f"max_seq_len {engine.cfg.max_seq_len}")
    out: List[List[float]] = []
    for lo in range(0, len(prompts), EMBED_MAX_BATCH):
        out.extend(_embed_batch(engine, prompts[lo:lo + EMBED_MAX_BATCH]))
    return out


# Per-forward row cap: bounds activation memory and the (B, T) compile
# variety to the same order as the serving path (engine batches are capped
# by cfg); larger request lists chunk through this.
EMBED_MAX_BATCH = 32


# bucket_fn
def _chunk_bucket(n: int, chunk: int = 1) -> int:
    """Round ``n`` up to ``chunk`` × a power of two: log-many compiled
    shapes per axis instead of one per chunk multiple (chunk=1 is a plain
    pow2 bucket). Extra padding is masked out downstream."""
    m = 1
    while m * chunk < n:
        m *= 2
    return m * chunk


def _embed_batch(engine: Engine, prompts: List[List[int]]) -> List[List[float]]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    chunk = engine.cfg.prefill_chunk
    longest = max(len(p) for p in prompts)
    # Both axes bucketed (log compile variety): T to chunk × pow2 — the
    # old chunk-multiple rounding compiled one program per multiple.
    T = _chunk_bucket(longest, chunk)
    B = _chunk_bucket(len(prompts))
    cache = getattr(engine, "_embed_cache", None)
    if cache is None:
        cache = engine._embed_cache = {}
    fn = cache.get((B, T))
    if fn is None:
        from rbg_tpu.models.llama import encode_hidden
        mcfg = engine.mcfg

        def pooled(params, toks, mask):
            # Pool in f32: bf16 models would accumulate the D-sum AND the
            # token count in bf16 (counts are exact only to 256 — long
            # prompts would mean-pool with the wrong divisor).
            h = encode_hidden(params, mcfg, toks, mask).astype(jnp.float32)
            m = mask[:, :, None].astype(jnp.float32)
            return (h * m).sum(1) / jnp.maximum(m.sum(1), 1.0)

        pooled.__name__ = names.PROGRAM_EMBED_POOLED   # jitwatch catalog
        fn = cache[(B, T)] = jax.jit(pooled)
    toks = np.zeros((B, T), np.int32)
    mask = np.zeros((B, T), bool)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
        mask[i, :len(p)] = True
    vecs = np.asarray(fn(engine.params, jnp.asarray(toks),
                         jnp.asarray(mask)), np.float32)
    return [vecs[i].tolist() for i in range(len(prompts))]


@_race_guard
class _BatchService:
    """Shared loop: subclasses implement ``_admit(item, sampling) -> rid``
    (raising on bad input fails just that request) and expose ``engine``.

    Overload protection (``max_queue``): submission into a full queue — or
    one whose estimated wait (from recent completion throughput) already
    exceeds the request's deadline budget — raises ``Overloaded`` with a
    ``retry_after_s`` hint instead of queueing unboundedly. Deadlines:
    queued entries whose budget expires before admission are dropped
    without ever touching the engine, and admitted rows past deadline are
    aborted ON the loop thread (slot + KV pages recycle immediately), so
    abandoned work never burns device steps."""

    engine: Engine
    # Role label the SLO judgments carry (per-role attainment aggregates
    # over it); DecodeService overrides.
    slo_role = "unified"

    def __init__(self, max_queue: Optional[int] = None):
        self.max_queue = max_queue
        # Per-request SLO judgment at finish (obs/slo.py): targets come
        # from the engine config; one judgment per FINISHED request —
        # blocking and streaming both finish through the loop below, so
        # this is the single site (the slo_accounted invariant).
        cfg = self.engine.cfg
        self.slo = SLOTracker(
            SLOTargets(ttft_s=cfg.slo_ttft_s, tpot_s=cfg.slo_tpot_s),
            component=type(self).__name__.lower())
        # guarded_by[engine.service_queue]
        self.counters = {"shed_total": 0, "deadline_queue_drops": 0,
                         "deadline_running_aborts": 0, "early_rejects": 0}
        # Predictive early rejection (Mooncake overload story): armed by
        # cfg.early_reject="auto" with a TTFT SLO target — admission
        # predicts TTFT (queue wait + prefill net of the prefix hit this
        # request would get) and sheds at INGRESS, before any prefill
        # compute is spent.
        self._early_reject = (cfg.early_reject == "auto"
                              and cfg.slo_ttft_s > 0)
        self._er_gate_s = cfg.slo_ttft_s * cfg.early_reject_factor
        # Measured prefill throughput (tokens/s EMA) — written by the
        # loop thread between steps, read racily by submitter threads
        # (a float read; staleness only skews one prediction). The
        # rate expires after _PF_RATE_TTL_S without a prefill window:
        # rejected requests do no prefill, so a stale-slow rate (e.g.
        # compile stalls on an unwarmed service) would otherwise shed
        # everything FOREVER — the rate could never re-learn.
        self._prefill_rate: Optional[float] = None
        self._pf_rate_t = 0.0
        self._pf_tokens = self.engine.metrics.get("prefill_tokens", 0)
        self._pf_t = time.monotonic()
        # Loop-thread-confined (admitted rows); deliberately NOT guarded.
        self._pending: Dict[int, _Pending] = {}
        self._lock = named_lock("engine.service_queue")
        self._wake = threading.Event()
        self._stopped = False
        # guarded_by[engine.service_queue]
        self._queue: List[Tuple[object, SamplingParams, _Pending]] = []
        self._cancels: List[_Pending] = []  # guarded_by[engine.service_queue]
        # Inbound KV stream receivers awaiting loop-thread adoption
        # (DecodeService.watch_stream fills it; _pump drains it).
        self._new_streams: List[object] = []  # guarded_by[engine.service_queue]
        self._done_times = collections.deque(maxlen=_RATE_WINDOW)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=type(self).__name__.lower())
        self._thread.start()

    # -- subclass hooks --
    def _admit(self, item, sampling: SamplingParams) -> Optional[int]:
        raise NotImplementedError

    def _pump(self) -> None:
        """Loop-thread hook before each iteration's engine work —
        DecodeService commits inbound KV stream chunks here."""

    def _ingress_prompt(self, item) -> Optional[List[int]]:
        """Prompt tokens of a submission, for the TTFT predictor — None
        when the item carries no prefill work this engine would run
        (e.g. a decode leg, whose prefill was already paid upstream)."""
        return None

    # -- admission control --

    def _completion_rate(self) -> Optional[float]:
        """Recent request completions per second (None = no estimate yet).
        Span is measured between the completions themselves — anchoring it
        to "now" would decay the rate through idle periods and make the
        estimated-wait gate shed the first requests after a lull."""
        d = self._done_times
        if len(d) < 2:
            return None
        span = d[-1] - d[0]
        if span <= 0:
            return None
        return (len(d) - 1) / span

    def estimated_wait_s(self, depth: Optional[int] = None) -> Optional[float]:
        """Expected queueing delay for a NEW submission, from the recent
        completion rate. None until enough history exists."""
        if depth is None:
            with self._lock:
                depth = len(self._queue)
        rate = self._completion_rate()
        if rate is None or rate <= 0:
            return None
        eng = self.engine
        backlog = depth + len(eng.running) + len(eng.waiting)
        return backlog / rate

    def _retry_after_hint(self, depth: int) -> float:
        est = self.estimated_wait_s(depth)
        return max(_RETRY_AFTER_FLOOR_S, est if est is not None else 1.0)

    def _note_prefill_progress(self) -> None:
        """Loop-thread sampling of prefill throughput between steps —
        only windows that actually prefilled update the EMA (idle
        windows must not decay the estimate toward zero and blind the
        predictor after a lull, the _completion_rate lesson)."""
        now = time.monotonic()
        dt = now - self._pf_t
        if dt < 0.2:
            return
        tp = self.engine.metrics.get("prefill_tokens", 0)
        if tp > self._pf_tokens:
            rate = (tp - self._pf_tokens) / dt
            stale = now - self._pf_rate_t > _PF_RATE_TTL_S
            self._prefill_rate = (
                rate if self._prefill_rate is None or stale
                else 0.7 * self._prefill_rate + 0.3 * rate)
            self._pf_rate_t = now
        self._pf_tokens, self._pf_t = tp, now

    def predicted_ttft_s(self, item,
                         depth: Optional[int] = None) -> Optional[float]:
        """Predicted TTFT for a NEW submission: measured queue wait plus
        this request's prefill time NET of the prefix-cache hit (device
        radix + host tier) it would get. None while either rate lacks
        history — the predictor never sheds on a guess."""
        est = self.estimated_wait_s(depth)
        prompt = self._ingress_prompt(item)
        rate = self._prefill_rate
        if (prompt is None or rate is None or rate <= 0
                or time.monotonic() - self._pf_rate_t > _PF_RATE_TTL_S):
            # No (or expired) throughput history: predict queue wait
            # only — the gate must never shed on a rate it cannot
            # re-measure.
            return est
        hit = self.engine.prefix_peek(list(prompt))
        prefill_s = max(0, len(prompt) - hit) / rate
        return prefill_s if est is None else est + prefill_s

    def _shed(self, msg: str, depth: int) -> None:
        self.counters["shed_total"] += 1
        REGISTRY.inc(names.SERVING_SHED_TOTAL,
                     service=type(self).__name__.lower())
        raise Overloaded(msg, retry_after_s=self._retry_after_hint(depth))

    # -- public --
    def submit_async(self, item, sampling: SamplingParams,
                     deadline: Optional[float] = None,
                     span=None, stream_rx=None) -> _Pending:
        """Enqueue one request. ``deadline`` is absolute ``time.monotonic()``
        seconds; raises ``Overloaded`` / ``DeadlineExceeded`` instead of
        queueing work that cannot be served. ``span`` (or the ambient
        current span) parents this request's queue-wait/scan spans; shed
        and deadline rejections still close their span — a refused request
        must leave a complete trace, not an orphan."""
        parent = span if span is not None else trace.current()
        qspan = parent.child(names.SPAN_SERVICE_QUEUE_WAIT)
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            with self._lock:
                self.counters["deadline_queue_drops"] += 1
            REGISTRY.inc(names.SERVING_DEADLINE_EXCEEDED_TOTAL, stage="queue")
            qspan.end(outcome="deadline")
            raise DeadlineExceeded("deadline already expired at submission")
        p = _Pending(deadline=deadline)
        p.span_parent = parent
        p.span_queue = qspan
        p.stream_rx = stream_rx
        try:
            with self._lock:
                # estimated_wait_s with an explicit depth never re-takes the
                # lock, so both gates may raise from inside it.
                depth = len(self._queue)
                if self.max_queue is not None and depth >= self.max_queue:
                    self._shed(f"service queue full ({self.max_queue})", depth)
                if deadline is not None:
                    est = self.estimated_wait_s(depth)
                    if est is not None and now + est >= deadline:
                        self._shed(
                            f"estimated wait {est:.2f}s exceeds remaining "
                            f"deadline budget {deadline - now:.2f}s", depth)
                if self._early_reject:
                    pred = self.predicted_ttft_s(item, depth)
                    if pred is not None:
                        svc = type(self).__name__.lower()
                        REGISTRY.observe(
                            names.SERVING_PREDICTED_TTFT_SECONDS, pred,
                            service=svc)
                        if pred > self._er_gate_s:
                            self.counters["early_rejects"] += 1
                            REGISTRY.inc(names.SERVING_EARLY_REJECTS_TOTAL,
                                         service=svc)
                            self._shed(
                                f"predicted TTFT {pred:.2f}s exceeds the "
                                f"early-reject gate {self._er_gate_s:.2f}s",
                                depth)
                self._queue.append((item, sampling, p))
                REGISTRY.observe(names.SERVING_QUEUE_DEPTH, depth + 1)
        except Rejected as e:
            qspan.end(outcome=e.code)
            raise
        self._wake.set()
        return p

    def submit_wave(self, items) -> List[_Pending]:
        """Atomically enqueue ``[(item, sampling), ...]`` so one loop
        iteration admits them together (up to max_batch) — warmup needs a
        deterministic batch composition, not whatever interleaving the
        wake races produce."""
        ps = []
        with self._lock:
            for item, sampling in items:
                p = _Pending()
                self._queue.append((item, sampling, p))
                ps.append(p)
        self._wake.set()
        return ps

    def _bucket_sizes(self) -> List[int]:
        eng = self.engine
        return sorted({eng._bucket(b)
                       for b in range(1, eng.cfg.max_batch + 1)},
                      reverse=True)

    def warmup(self, input_len: int = 32, out_len: int = 2) -> float:
        """Compile every decode/prefill bucket jit variant before traffic.

        A variant first hit mid-serving stalls EVERY in-flight request for
        the compile (seconds on CPU, tens of seconds at real model sizes) —
        measured here as a 9x throughput swing between identical bench
        runs, and the gap the reference closes with warmup pods
        (``rolebasedgroup_controller.go`` buildWarmupPod:535; our control
        plane's warmup controller readies images, this readies the jit
        cache). One wave per bucket size, largest first, through the
        normal submit path. Returns elapsed seconds."""
        t0 = time.monotonic()
        # Ragged unified shapes first (all-pad dispatches, cache
        # untouched) — the prompt waves below only hit the packed-token
        # buckets their own composition happens to produce.
        self.engine.warm_ragged()
        for B in self._bucket_sizes():
            items = [(self._warm_item(input_len, B, i),
                      SamplingParams(max_new_tokens=out_len))
                     for i in range(B)]
            for p in self.submit_wave(items):
                self.wait(p, 600.0)
        # The waves only compiled the fused-decode and sampler variants
        # their own composition hit (default sampling, wave-sized
        # buckets); warm_decode/warm_samplers cover the full plain
        # bucket × top-p grid — the gap the jitwatch sentry surfaced.
        self.engine.warm_decode()
        # The waves compiled the K=multi_step fused programs; the K=1
        # early-exit twins (_decode_window's join shortening) would
        # otherwise first compile MID-SERVING, on the join-latency path.
        self.engine.warm_join_windows()
        self.engine.warm_samplers()
        # Arm the jitwatch gate (no-op unless RBG_JITWATCH armed the
        # hooks): everything compiled above is the blessed warmup set;
        # any cataloged program compiling after this is a violation.
        jitwatch.warmup_complete()
        # The warm waves were compile-laden: their token throughput is
        # not serving throughput, and an early-reject predictor trained
        # on it would shed the first real traffic. Reset so the EMA
        # learns from warm steps only.
        self._prefill_rate = None
        self._pf_tokens = self.engine.metrics.get("prefill_tokens", 0)
        self._pf_t = time.monotonic()
        return time.monotonic() - t0

    def _warm_item(self, input_len: int, wave: int, row: int):
        raise NotImplementedError

    def wait(self, p: _Pending, timeout: float) -> List[int]:
        if not p.done.wait(timeout):
            self.cancel(p)  # recycle batch slot + KV pages, don't orphan
            raise TimeoutError("generation timed out")
        if p.error:
            if p.code == CODE_DEADLINE:
                raise DeadlineExceeded(p.error)
            raise ValueError(p.error)
        return p.tokens

    def submit_wait(self, item, sampling: SamplingParams,
                    timeout: float = DEFAULT_TIMEOUT_S,
                    deadline: Optional[float] = None,
                    span=None) -> _Pending:
        """Blocking submit; returns the completed _Pending (tokens,
        logprobs, ttft timestamps). The one blocking-wait/timeout contract
        every caller — server ops included — goes through. ``deadline``
        (absolute monotonic) bounds the whole stay: admission gate, queue
        drop, AND engine-side abort, not just this thread's wait."""
        p = self.submit_async(item, sampling, deadline=deadline, span=span)
        if deadline is not None:
            timeout = min(timeout, max(0.0, deadline - time.monotonic()) + 1.0)
        self.wait(p, timeout)
        return p

    @staticmethod
    def ttft(p: _Pending) -> float:
        return (p.t_first - p.t_submit) if p.t_first else 0.0

    def service_stats(self) -> dict:
        """Admission-control / lifecycle counters (merged into the metrics
        op by every serving mode, scraped by the stress harness)."""
        with self._lock:
            depth = len(self._queue)
            out = dict(self.counters)
        est = self.estimated_wait_s(depth)
        out["queue_depth"] = depth
        out["max_queue"] = self.max_queue
        out["estimated_wait_s"] = round(est, 4) if est is not None else None
        out["slo_judged_total"] = self.slo.judged_total()
        pf = self._prefill_rate
        out["prefill_tokens_per_s"] = round(pf, 2) if pf is not None else None
        out["early_reject_armed"] = self._early_reject
        return out

    def cancel(self, pending: _Pending) -> None:
        """Abort an in-flight request (routed through the loop thread)."""
        with self._lock:
            self._cancels.append(pending)
        self._wake.set()

    def stop(self):
        self._stopped = True
        self._wake.set()
        # Join so stop() actually frees the CPU: a "stopped" service whose
        # loop thread lingers keeps polling (and in a test suite, dozens of
        # leaked loops become ambient load that starves later tests).
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=30.0)

    # -- loop --
    def _expire_queue_locked(self, now: float) -> List[_Pending]:
        """Drop queued entries whose deadline passed before admission.
        Caller holds the lock; the dropped pendings are failed OUTSIDE it."""
        if not any(p.deadline is not None for _, _, p in self._queue):
            return []
        live, dead = [], []
        for entry in self._queue:
            p = entry[2]
            if p.deadline is not None and now >= p.deadline:
                dead.append(p)
            else:
                live.append(entry)
        self._queue = live
        return dead

    def _abort_expired_running(self, now: float) -> None:
        """Abort admitted rows past deadline (loop thread — the only thread
        allowed to touch the engine): the slot and KV pages recycle NOW
        instead of burning device steps to max_new_tokens."""
        expired = [(rid, p) for rid, p in self._pending.items()
                   if p.deadline is not None and now >= p.deadline]
        if expired:
            with self._lock:
                self.counters["deadline_running_aborts"] += len(expired)
        for rid, p in expired:
            self.engine.cancel_request(rid)
            del self._pending[rid]
            REGISTRY.inc(names.SERVING_DEADLINE_EXCEEDED_TOTAL,
                         stage="running")
            p.error = "deadline exceeded mid-generation (aborted)"
            p.code = CODE_DEADLINE
            p.span_scan.end(outcome="deadline_abort",
                            tokens=len(p.tokens))
            p.done.set()

    def _judge_finished(self, pending: _Pending, t_done: float) -> None:
        """SLO-judge ONE finished request (loop thread). TTFT measures
        submission → first token; TPOT is the mean per-token latency
        after the first (0 for single-token outputs — trivially met).
        Every finished request passes here exactly once, and only
        finished requests do (deadline aborts, cancels, and admit errors
        are accounted under their own counters, not judged)."""
        n = len(pending.tokens)
        if pending.t_first is not None:
            ttft = pending.t_first - pending.t_submit
            tpot = ((t_done - pending.t_first) / (n - 1)) if n > 1 else 0.0
        else:
            # Finished without a streamed token (e.g. a decode bundle
            # completed at inject): its whole stay is the TTFT.
            ttft = t_done - pending.t_submit
            tpot = 0.0
        self.slo.judge(ttft, tpot, role=self.slo_role)
        svc = type(self).__name__.lower()
        REGISTRY.inc(names.SERVING_REQUESTS_FINISHED_TOTAL, service=svc)
        if n:
            REGISTRY.inc(names.SERVING_TOKENS_TOTAL, float(n), service=svc)

    def _loop(self):
        eng = self.engine
        while not self._stopped:
            now = time.monotonic()
            with self._lock:
                cancels = self._cancels
                self._cancels = []
                expired = self._expire_queue_locked(now)
                # Admission control: never exceed the engine's batch ceiling —
                # excess items stay queued for later rounds.
                budget = max(0, eng.cfg.max_batch
                             - len(eng.running) - len(eng.waiting))
                newly = self._queue[:budget]
                self._queue = self._queue[budget:]
                # Continuous batching: submissions still queued beyond this
                # step's budget shorten the engine's fused decode window so
                # the next free slot absorbs them at step granularity.
                eng.join_hint = bool(self._queue)
            if expired:
                with self._lock:
                    self.counters["deadline_queue_drops"] += len(expired)
            for pending in expired:
                REGISTRY.inc(names.SERVING_DEADLINE_EXCEEDED_TOTAL,
                             stage="queue")
                pending.error = "deadline expired before admission"
                pending.code = CODE_DEADLINE
                pending.span_queue.end(outcome="deadline_dropped")
                pending.done.set()
            for item, sampling, pending in newly:
                pending.span_queue.end(outcome="admitted")
                scan = pending.span_scan = pending.span_parent.child(
                    names.SPAN_SERVICE_SCAN)
                try:
                    if pending.span_parent:
                        # Ambient span so hop internals (e.g. the decode
                        # bundle KV-import in pd.py) attach their own
                        # children without signature plumbing.
                        with trace.use_span(pending.span_parent):
                            rid = self._admit(item, sampling)
                    else:
                        rid = self._admit(item, sampling)
                except Exception as e:
                    # A bad request must fail ITSELF, never the loop thread.
                    scan.end(outcome="admit_error")
                    pending.error = str(e)
                    # Structured failure classes (e.g. a dead KV stream's
                    # kv_stream_failed) keep their wire code so the router
                    # can recognize and recover instead of passing a raw
                    # error to the client.
                    pending.code = getattr(e, "wire_code", None)
                    pending.done.set()
                    continue
                if rid is None:
                    scan.end(outcome="done_at_admit")
                    self._judge_finished(pending, time.perf_counter())
                    pending.done.set()  # completed at admission
                    self._done_times.append(time.monotonic())
                    continue
                self._pending[rid] = pending
            self._abort_expired_running(now)
            self._pump()
            for pending in cancels:
                rid = next((r for r, p in self._pending.items() if p is pending),
                           None)
                if rid is not None:
                    eng.cancel_request(rid)
                    del self._pending[rid]
                    pending.span_scan.end(outcome="cancelled")
                    pending.done.set()
                else:
                    # Still queued (never admitted) — drop it from the queue.
                    with self._lock:
                        self._queue = [q for q in self._queue if q[2] is not pending]
                    pending.span_queue.end(outcome="cancelled")
                    pending.done.set()
            if not eng.has_work():
                with self._lock:
                    empty = not self._queue and not self._cancels
                if empty:
                    # Idle time must not enter the prefill-rate window:
                    # the first active window after a lull would
                    # otherwise measure chunk_tokens / lull_length, and
                    # (past the TTL) REPLACE the EMA with that near-zero
                    # rate — shedding the whole next burst.
                    self._pf_t = time.monotonic()
                    self._pf_tokens = eng.metrics.get("prefill_tokens", 0)
                    self._wake.wait(0.01)
                    self._wake.clear()
                continue
            events = eng.step()
            self._note_prefill_progress()
            # Batch-occupancy / join-latency observability (one occupancy
            # sample per step; join waits are recorded by the engine at
            # admission and drained here — both loop-thread-confined).
            REGISTRY.observe(names.SERVING_BATCH_OCCUPANCY,
                             len(eng.running) / max(1, eng.cfg.max_batch),
                             service=type(self).__name__.lower())
            if eng.last_join_waits:
                for w in eng.last_join_waits:
                    REGISTRY.observe(names.SERVING_JOIN_LATENCY_SECONDS, w,
                                     service=type(self).__name__.lower())
                eng.last_join_waits.clear()
            for ev in events:
                pending = self._pending.get(ev.request_id)
                if pending is None:
                    continue
                if pending.t_first is None:
                    pending.t_first = time.perf_counter()
                    if pending.stream_rx is not None \
                            and pending.stream_rx.t_first_step is None:
                        # First DECODE step of a streamed row — the
                        # kv_stream_overlap invariant compares this
                        # against the stream's FIN arrival.
                        pending.stream_rx.t_first_step = time.monotonic()
                pending.tokens.append(ev.token)
                if ev.logprob is not None:
                    pending.logprobs.append(ev.logprob)
                if ev.finished:
                    pending.span_scan.end(outcome="ok",
                                          tokens=len(pending.tokens))
                    t_done = time.perf_counter()
                    REGISTRY.observe(
                        names.SERVING_REQUEST_DURATION_SECONDS,
                        t_done - pending.t_submit,
                        exemplar=pending.span_scan.trace_id or None,
                        service=type(self).__name__.lower())
                    self._judge_finished(pending, t_done)
                    pending.done.set()
                    del self._pending[ev.request_id]
                    # Completion history feeds the estimated-wait gate.
                    self._done_times.append(time.monotonic())


class EngineService(_BatchService):
    def __init__(self, cfg: EngineConfig, params=None, mesh=None,
                 max_queue: Optional[int] = None):
        self.engine = Engine(cfg, params=params, mesh=mesh)
        super().__init__(max_queue=max_queue)

    def _admit(self, prompt, sampling: SamplingParams) -> Optional[int]:
        return self.engine.add_request(prompt, sampling)

    def _ingress_prompt(self, item) -> Optional[List[int]]:
        return item if isinstance(item, (list, tuple)) else None

    def _warm_item(self, input_len: int, wave: int, row: int):
        from rbg_tpu.engine.config import warm_prompt
        return warm_prompt(input_len, wave, row)

    def submit(self, prompt: List[int], sampling: SamplingParams,
               timeout: float = DEFAULT_TIMEOUT_S,
               deadline: Optional[float] = None) -> Tuple[List[int], float]:
        """Blocking generate. Returns (tokens, ttft_seconds)."""
        p = self.submit_wait(prompt, sampling, timeout, deadline=deadline)
        return p.tokens, self.ttft(p)

    def embed(self, prompt: List[int]) -> List[float]:
        """Mean-pooled final-norm hidden state for one prompt."""
        return embed_prompts(self.engine, [prompt])[0]

    def stats(self) -> dict:
        out = dict(self.engine.metrics)
        out["running"] = len(self.engine.running)
        out["waiting"] = len(self.engine.waiting)
        out["free_pages"] = self.engine.allocator.free_pages
        out["radix_nodes"] = (self.engine.radix.num_nodes
                              if self.engine.radix is not None else 0)
        out.update(self.service_stats())
        return out


class DecodeService(_BatchService):
    """Disaggregated decode role: KV bundles from many router connections
    decode TOGETHER on the device instead of serializing per connection."""

    slo_role = "decode"

    def __init__(self, cfg, params=None, mesh=None,
                 max_queue: Optional[int] = None):
        from rbg_tpu.engine.pd import DecodeWorker
        from rbg_tpu.kvtransfer.stream import StreamRegistry

        self.worker = DecodeWorker(cfg, params=params, mesh=mesh)
        self.engine = self.worker.engine
        # Inbound KV chunk streams (the decode server's kv_stream op feeds
        # these; decode_stream requests consume them).
        self.kv_streams = StreamRegistry()
        super().__init__(max_queue=max_queue)

    def watch_stream(self, receiver) -> None:
        """Ask the loop thread to start committing this stream's chunks
        into the page table AS THEY ARRIVE (before admission) — callable
        from any connection thread."""
        with self._lock:
            self._new_streams.append(receiver)
        self._wake.set()

    def _pump(self) -> None:
        with self._lock:
            new, self._new_streams = self._new_streams, []
        for rx in new:
            self.worker.begin_stream(rx)
        self.worker.pump_streams()

    def submit_stream(self, receiver, sampling: SamplingParams,
                      deadline: Optional[float] = None,
                      span=None) -> _Pending:
        """Admit a coverage-complete KV stream (caller waited on
        ``receiver.wait_ready``) into the decode batch."""
        return self.submit_async(receiver, sampling, deadline=deadline,
                                 span=span, stream_rx=receiver)

    def _admit(self, item, sampling: SamplingParams) -> Optional[int]:
        from rbg_tpu.kvtransfer.stream import KVStreamReceiver

        if isinstance(item, KVStreamReceiver):
            rid = self.worker.finalize_stream(item, sampling)
            self.kv_streams.pop(item.stream_id)
        else:
            rid = self.worker.inject(item, sampling)
        req = self.engine.requests.get(rid)
        if req is None or req.state == "finished":
            return None  # completed at inject (max_new_tokens == 1 / stop)
        return rid

    def _warm_item(self, input_len: int, wave: int, row: int):
        """A zero-KV bundle with the serving page count: compiles the
        inject scatter (keyed on n_pages) and the decode buckets. Numerics
        are irrelevant to compilation; the zero pages are released when
        the warm request finishes."""
        import numpy as np

        from rbg_tpu.engine.kvcache import pages_for_tokens
        from rbg_tpu.engine.pd import KVBundle

        from rbg_tpu.engine.config import warm_prompt

        eng = self.engine
        n_pages = pages_for_tokens(input_len, eng.cfg.page_size)
        # k and v bundle halves take their OWN pool's shape/dtype: under
        # MLA the v pool holds the shared RoPE key (different channel dim
        # than the k latent) — deriving both from k_pages made every MLA
        # decode replica fail its {"op": "warmup"}.
        kshape = eng.cache.k_pages.shape
        vshape = eng.cache.v_pages.shape
        return KVBundle(
            prompt=warm_prompt(input_len, wave, row), first_token=1,
            k_data=np.zeros((kshape[0], n_pages) + kshape[2:],
                            np.dtype(eng.cache.k_pages.dtype)),
            v_data=np.zeros((vshape[0], n_pages) + vshape[2:],
                            np.dtype(eng.cache.v_pages.dtype)))

    def submit_bundle(self, bundle, sampling: SamplingParams,
                      timeout: float = DEFAULT_TIMEOUT_S) -> List[int]:
        """Blocking decode of an injected bundle (first token included)."""
        p = self.submit_wait(bundle, sampling, timeout)
        return [bundle.first_token] + p.tokens
