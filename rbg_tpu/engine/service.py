"""EngineService: background continuous-batching loop + blocking submit API.

Requests arriving on different connections batch together on the device —
the server threads only enqueue and wait; one loop thread owns the engine
(single-writer, no engine locking on the hot path).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.engine import Engine


class _Pending:
    __slots__ = ("tokens", "done", "t_submit", "t_first")

    def __init__(self):
        self.tokens: List[int] = []
        self.done = threading.Event()
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None


class EngineService:
    def __init__(self, cfg: EngineConfig, params=None, mesh=None):
        self.engine = Engine(cfg, params=params, mesh=mesh)
        self._pending: Dict[int, _Pending] = {}
        self._lock = threading.Lock()          # guards queue handoff only
        self._wake = threading.Event()
        self._stop = False
        self._queue: List[Tuple[List[int], SamplingParams, _Pending]] = []
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="engine-loop")
        self._thread.start()

    def submit(self, prompt: List[int], sampling: SamplingParams,
               timeout: float = 600.0) -> Tuple[List[int], float]:
        """Blocking generate. Returns (tokens, ttft_seconds)."""
        p = self.submit_async(prompt, sampling)
        if not p.done.wait(timeout):
            raise TimeoutError("generation timed out")
        return p.tokens, (p.t_first - p.t_submit if p.t_first else 0.0)

    def submit_async(self, prompt: List[int], sampling: SamplingParams) -> _Pending:
        """Enqueue and return the live Pending (stream by watching .tokens
        grow until .done is set)."""
        p = _Pending()
        with self._lock:
            self._queue.append((prompt, sampling, p))
        self._wake.set()
        return p

    def stats(self) -> dict:
        out = dict(self.engine.metrics)
        out["running"] = len(self.engine.running)
        out["waiting"] = len(self.engine.waiting)
        out["free_pages"] = self.engine.allocator.free_pages
        out["radix_nodes"] = (self.engine.radix.num_nodes
                              if self.engine.radix is not None else 0)
        return out

    def stop(self):
        self._stop = True
        self._wake.set()

    def _loop(self):
        eng = self.engine
        while not self._stop:
            with self._lock:
                newly = self._queue
                self._queue = []
            for prompt, sampling, pending in newly:
                rid = eng.add_request(prompt, sampling)
                self._pending[rid] = pending
            if not eng.has_work():
                self._wake.wait(0.01)
                self._wake.clear()
                continue
            for ev in eng.step():
                pending = self._pending.get(ev.request_id)
                if pending is None:
                    continue
                if pending.t_first is None:
                    pending.t_first = time.perf_counter()
                pending.tokens.append(ev.token)
                if ev.finished:
                    pending.done.set()
                    del self._pending[ev.request_id]
