"""Router tier: N routers behind consistent-hash affinity — SPOF #1 killed.

One ``engine/router.py`` process was a single point of failure AND a
signal silo: its prefix-affinity LRU, measured KV link rates, and the
ingress token counters the topology policy steers on all lived in one
process. This module makes a TIER out of N routers:

* :class:`HashRing` — consistent hashing with virtual nodes. The same
  session/prefix key always lands on the replica whose affinity LRU is
  warm; removing a member moves ONLY that member's ranges (its keys
  re-hash to ring successors, everyone else's stay put).
* :class:`RouterTier` — membership + the router-to-router event feed.
  Peers learn backend health/draining transitions and measured
  ``rbg_kvtransfer_link_bytes_per_s`` rates from each other instead of
  rediscovering them per-process, and ingress token counts AGGREGATE
  across members so ``TopologyPolicy`` sees the whole mix, not one
  router's partial view (``topology.signals.tier_ingress_ratio``).
  Routing is consistent-hash first with bounded-load fallback: an
  overloaded or draining owner spills its key to the next ring
  successor instead of hot-spotting.
* :class:`TierClient` — the kill-a-router drill's session driver:
  sessions pin their sampling seed CLIENT-SIDE on the first attempt, so
  when a member dies mid-stream the re-hashed replay is token-exact
  (position-keyed PRNG) and the already-delivered prefix is skipped —
  the PR-10 bundle-fallback replay contract, one hop up.

The tier object is process-local coordination (the drill and embedded
multi-router deployments); the wire form of the same feed is the
``peer_event`` admin op each router serves.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs import trace
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_lock

__all__ = ["HashRing", "RouterTier", "TierClient", "MemberDown"]

# Virtual nodes per member: enough that a 3-member tier splits the key
# space within a few percent of even, small enough that ring rebuilds
# (member join/leave) stay trivially cheap.
VNODES = 64

# Bounded-load factor (the "power of consistent-hash with bounded loads"
# bound): an owner carrying more than factor x the tier-mean outstanding
# load spills NEW keys to its ring successor. 1.25 is the classic choice.
BOUNDED_LOAD_FACTOR = 1.25

# Peer-feed event kinds.
EV_HEALTH = "health"            # backend up/down transition
EV_DRAINING = "draining"        # backend OR router draining transition
EV_LINK_RATES = "link_rates"    # measured kvtransfer link rates
EV_INGRESS = "ingress"          # ingress token counts (prefill/decode)


def _digest(key: str) -> int:
    """Deterministic 64-bit ring position (NOT ``hash()``: that is
    per-process salted and would shred affinity across restarts)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring with virtual nodes over member names."""

    def __init__(self, vnodes: int = VNODES):
        self.vnodes = vnodes
        self._members: set = set()
        self._ring: List[Tuple[int, str]] = []   # sorted (digest, member)
        self._keys: List[int] = []               # digests only, for bisect

    def members(self) -> List[str]:
        return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, name: str) -> bool:
        return name in self._members

    def add(self, name: str) -> None:
        if name in self._members:
            return
        self._members.add(name)
        for i in range(self.vnodes):
            self._ring.append((_digest(f"{name}#{i}"), name))
        self._ring.sort()
        self._keys = [d for d, _ in self._ring]

    def remove(self, name: str) -> None:
        if name not in self._members:
            return
        self._members.discard(name)
        self._ring = [(d, m) for d, m in self._ring if m != name]
        self._keys = [d for d, _ in self._ring]

    def owner(self, key: str) -> Optional[str]:
        """The member owning ``key`` (first vnode clockwise)."""
        if not self._ring:
            return None
        i = bisect.bisect(self._keys, _digest(key)) % len(self._ring)
        return self._ring[i][1]

    def owners(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct members in clockwise fallback order from ``key`` —
        ``owners(k)[0] == owner(k)``; a dead/draining owner's traffic
        spills to ``[1]``, which is exactly who inherits the range when
        the owner leaves the ring (minimal-movement fallback)."""
        if not self._ring:
            return []
        want = len(self._members) if n is None else min(n, len(self._members))
        out: List[str] = []
        start = bisect.bisect(self._keys, _digest(key))
        for j in range(len(self._ring)):
            m = self._ring[(start + j) % len(self._ring)][1]
            if m not in out:
                out.append(m)
                if len(out) >= want:
                    break
        return out


class _Member:
    __slots__ = ("name", "state", "draining", "outstanding", "ingress",
                 "link_rates", "peer_events", "last_seen", "ingress_last")

    def __init__(self, name: str, state=None, now: float = 0.0):
        self.name = name
        self.state = state           # optional RouterState back-reference
        self.draining = False
        self.outstanding = 0
        self.ingress = {"prefill": 0.0, "decode": 0.0}
        self.link_rates: Dict[str, float] = {}
        self.peer_events = 0
        # Last instant this member showed life on the feed (registration,
        # any published event, its own ingress notes) — the per-peer
        # staleness TTL ages routing eligibility off it.
        self.last_seen = now
        # Per-kind last CUMULATIVE ingress totals seen from this member's
        # EV_INGRESS events — the counter-restart fold's watermark.
        self.ingress_last: Dict[str, float] = {}


class MemberDown(Exception):
    """A routed member died mid-stream (drill injection / dead peer)."""


class RouterTier:
    """Membership, routing, and the peer event feed for N routers.

    Everything here is guarded by one lock (``named_lock("engine.tier")``)
    except peer delivery callbacks, which run OUTSIDE it — a member's
    ``on_peer_event`` may call back into the tier (e.g. merge link rates
    then publish its own transition) without deadlocking.
    """

    def __init__(self, name: str = "tier", vnodes: int = VNODES,
                 bounded_load: float = BOUNDED_LOAD_FACTOR,
                 clock: Optional[Callable[[], float]] = None,
                 peer_stale_after_s: Optional[float] = None):
        self.name = name
        self.ring = HashRing(vnodes)
        self.bounded_load = float(bounded_load)
        # Per-peer staleness TTL: a member silent on the feed for longer
        # than this is EXCLUDED from routing (a router must not steer at
        # backends whose health it can no longer observe) until it speaks
        # again. None (default) = off — single-process tiers with no
        # heartbeat traffic must not age themselves out.
        self.peer_stale_after_s = peer_stale_after_s
        self._clock = clock or time.monotonic
        self._lock = named_lock("engine.tier")
        self._members: Dict[str, _Member] = {}   # guarded_by[engine.tier]
        # Ingress sample log for windowed cross-router rates:
        # (t, member, kind, n) appended by note_ingress.
        self._ingress_log: deque = deque(maxlen=65536)  # guarded_by[engine.tier]
        self.events_published = 0                # guarded_by[engine.tier]

    # -- membership --

    def register(self, name: str, state=None) -> None:
        """Add a router to the ring. ``state`` (a ``RouterState``) makes
        the member an in-process peer: events fan in through its
        ``on_peer_event``."""
        with self._lock:
            now = self._clock()
            if name not in self._members:
                self._members[name] = _Member(name, state, now=now)
                self.ring.add(name)
            else:
                m = self._members[name]
                m.last_seen = now
                if state is not None:
                    m.state = state
            n = len(self.ring)
        REGISTRY.set_gauge(obs_names.ROUTER_RING_MEMBERS, float(n),
                           tier=self.name)

    def remove(self, name: str) -> None:
        """Member leaves (crash or drained-out): its hash ranges move to
        ring successors — a reshard event."""
        with self._lock:
            existed = self._members.pop(name, None) is not None
            self.ring.remove(name)
            n = len(self.ring)
        if existed:
            span = trace.start_trace(obs_names.SPAN_ROUTER_RESHARD,
                                     tier=self.name, left=name)
            REGISTRY.inc(obs_names.ROUTER_RING_RESHARDS_TOTAL,
                         tier=self.name)
            REGISTRY.set_gauge(obs_names.ROUTER_RING_MEMBERS, float(n),
                               tier=self.name)
            span.end(outcome="resharded", members=n)

    def members(self) -> List[str]:
        with self._lock:
            return self.ring.members()

    # -- routing --

    def route(self, key: str) -> Optional[str]:
        """Pick the serving router for ``key``: ring owner unless it is
        draining, gone, or over the bounded-load limit — then the first
        eligible ring successor (consistent spill: the same overloaded
        key always spills to the same peer). Returns None on an empty
        tier."""
        stale_cut = None
        n_stale = 0
        with self._lock:
            if self.peer_stale_after_s is not None:
                stale_cut = self._clock() - self.peer_stale_after_s
                n_stale = sum(1 for m in self._members.values()
                              if m.last_seen < stale_cut)
            order = self.ring.owners(key)
            if not order:
                return None
            loads = {m.name: m.outstanding for m in self._members.values()}
            mean = (sum(loads.values()) / len(loads)) if loads else 0.0
            limit = max(self.bounded_load * mean, 1.0)
            pick = None
            for cand in order:
                m = self._members.get(cand)
                if m is None or m.draining:
                    continue
                if stale_cut is not None and m.last_seen < stale_cut:
                    # Silent past the TTL: maybe partitioned, maybe dead —
                    # either way its health view is fiction. Its ranges
                    # spill to ring successors until it speaks again.
                    continue
                if pick is None:
                    pick = cand      # first non-draining = fallback floor
                if m.outstanding <= limit:
                    pick = cand
                    break
        if stale_cut is not None:
            # Tier-level, not per-decision: ANY stale member means the
            # ladder rung is engaged (its ranges are spilling), whether
            # or not this particular key's walk touched it.
            REGISTRY.set_gauge(obs_names.DEGRADED_MODE,
                               1.0 if n_stale else 0.0,
                               ladder="peer_feed")
        if pick is not None:
            REGISTRY.inc(obs_names.ROUTER_RING_ROUTES_TOTAL,
                         tier=self.name, member=pick)
        return pick

    def acquire(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.outstanding += 1

    def release(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None and m.outstanding > 0:
                m.outstanding -= 1

    # -- peer event feed --

    def publish(self, origin: str, kind: str, payload: dict) -> int:
        """Fan an event from ``origin`` out to every OTHER member's
        ``on_peer_event`` (delivery outside the tier lock). Returns the
        number of peers reached."""
        ev = {"tier": self.name, "origin": origin, "kind": kind,
              "payload": payload, "t": self._clock()}
        with self._lock:
            self.events_published += 1
            m = self._members.get(origin)
            if m is not None:
                # Any event is proof of life — the staleness TTL feeds
                # off this watermark.
                m.last_seen = ev["t"]
            if kind == EV_DRAINING and "router" in payload:
                if m is not None:
                    m.draining = bool(payload.get("draining"))
            if kind == EV_LINK_RATES:
                if m is not None:
                    for a, r in (payload.get("rates") or {}).items():
                        try:
                            m.link_rates[a] = float(r)
                        except (TypeError, ValueError):
                            continue
            if kind == EV_INGRESS and m is not None:
                # Payload carries CUMULATIVE per-kind totals. Fold the
                # delta against this member's watermark; a total BELOW
                # the watermark is a counter restart (the member came
                # back under the same --router-id with zeroed counters,
                # PR-8 convention) — fold the full new value, never a
                # negative delta that would poison the topology ratio.
                for k, tot in (payload.get("totals") or {}).items():
                    try:
                        tot = float(tot)
                    except (TypeError, ValueError):
                        continue
                    last = m.ingress_last.get(k)
                    delta = tot if (last is None or tot < last) \
                        else tot - last
                    m.ingress_last[k] = tot
                    if delta > 0:
                        m.ingress[k] = m.ingress.get(k, 0.0) + delta
                        self._ingress_log.append((ev["t"], origin, k,
                                                  delta))
            targets = [mm for n, mm in self._members.items() if n != origin]
        delivered = 0
        for m in targets:
            st = m.state
            handler = getattr(st, "on_peer_event", None)
            if handler is None:
                continue
            try:
                handler(ev)
                delivered += 1
                with self._lock:
                    m.peer_events += 1
            except Exception:
                continue
        REGISTRY.inc(obs_names.ROUTER_PEER_EVENTS_TOTAL,
                     tier=self.name, kind=kind)
        return delivered

    def set_draining(self, name: str, draining: bool = True) -> None:
        """Router-level drain transition (the PR-2 SIGTERM protocol's
        tier half): the member stops taking NEW keys — ``route`` spills
        its ranges to ring successors — while its in-flight streams run
        to completion; peers learn via the feed."""
        with self._lock:
            m = self._members.get(name)
            if m is None or m.draining == draining:
                return
            m.draining = draining
        self.publish(name, EV_DRAINING,
                     {"router": name, "draining": draining})

    def draining(self, name: str) -> bool:
        with self._lock:
            m = self._members.get(name)
            return bool(m is not None and m.draining)

    # -- cross-router ingress aggregation --

    def note_ingress(self, name: str, kind: str, n: float,
                     now: Optional[float] = None) -> None:
        """Record ``n`` ingress tokens of ``kind`` observed by member
        ``name`` — the per-router counter's tier-shared twin. The
        topology ratio MUST read the tier sum: N routers each see 1/N of
        the mix, and any single router's ratio is noise."""
        if n <= 0:
            return
        t = self._clock() if now is None else now
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.ingress[kind] = m.ingress.get(kind, 0.0) + float(n)
                m.last_seen = max(m.last_seen, t)  # its own heartbeat
            self._ingress_log.append((t, name, kind, float(n)))

    def ingress_totals(self) -> Dict[str, float]:
        """Cumulative tokens per kind summed across ALL members."""
        out: Dict[str, float] = {"prefill": 0.0, "decode": 0.0}
        with self._lock:
            for m in self._members.values():
                for k, v in m.ingress.items():
                    out[k] = out.get(k, 0.0) + v
        return out

    def ingress_rates(self, window_s: float = 60.0,
                      now: Optional[float] = None) -> Dict[str, Optional[float]]:
        """Windowed tokens/s per kind, summed across members; a kind with
        NO samples in the window is ``None`` (absence of signal), never
        0.0 — the SignalReader discipline."""
        t = self._clock() if now is None else now
        lo = t - window_s
        sums: Dict[str, float] = {}
        seen: set = set()
        with self._lock:
            for ts, _name, kind, n in self._ingress_log:
                if ts < lo or ts > t:
                    continue
                seen.add(kind)
                sums[kind] = sums.get(kind, 0.0) + n
        return {k: (sums.get(k, 0.0) / window_s if k in seen else None)
                for k in ("prefill", "decode")}

    # -- introspection --

    def snapshot(self) -> dict:
        with self._lock:
            now = self._clock()
            members = {
                n: {"draining": m.draining, "outstanding": m.outstanding,
                    "ingress": dict(m.ingress), "peer_events": m.peer_events,
                    "silent_s": round(max(0.0, now - m.last_seen), 3),
                    "stale": bool(self.peer_stale_after_s is not None
                                  and now - m.last_seen
                                  > self.peer_stale_after_s),
                    "link_rates": {a: round(r, 1)
                                   for a, r in m.link_rates.items()}}
                for n, m in self._members.items()}
            return {"tier": self.name, "members": members,
                    "ring": self.ring.members(),
                    "events_published": self.events_published,
                    "peer_stale_after_s": self.peer_stale_after_s,
                    "bounded_load": self.bounded_load}


class TierClient:
    """Session driver for the kill-a-router drill (and tier tests).

    ``token_fn(seed, pos)`` is the position-keyed PRNG stand-in: token at
    ``pos`` is a pure function of (seed, pos), matching the engine's
    replay-deterministic sampling — which is exactly why a re-hashed
    replay is token-exact. The seed is pinned CLIENT-SIDE on session
    open (the router's ``_pin_seed`` one hop up), so no router holds
    irreplaceable session state.

    ``deliver_fn(member, session_key, seed, start_pos, n)`` produces the
    next ``n`` tokens from ``member`` starting at ``start_pos``; it
    raises :class:`MemberDown` when the member has been killed — the
    client then re-routes via the ring (the dead member is gone from it)
    and resumes from ``len(delivered)``, skipping nothing and repeating
    nothing."""

    def __init__(self, tier: RouterTier, token_fn: Callable[[int, int], int],
                 deliver_fn=None):
        self.tier = tier
        self.token_fn = token_fn
        self.deliver_fn = deliver_fn or self._default_deliver
        self.rehashes = 0
        self.failed = 0

    def _default_deliver(self, member: str, key: str, seed: int,
                         start: int, n: int) -> List[int]:
        if member not in self.tier.ring:
            raise MemberDown(member)
        return [self.token_fn(seed, p) for p in range(start, start + n)]

    def run_session(self, key: str, seed: int, total: int,
                    chunk: int = 8) -> dict:
        """Stream ``total`` tokens for session ``key``; survive member
        loss by re-routing + replaying. Returns {tokens, members, rehashes,
        delivered}."""
        delivered: List[int] = []
        members_used: List[str] = []
        rehashes = 0
        while len(delivered) < total:
            member = self.tier.route(key)
            if member is None:
                self.failed += 1
                raise RuntimeError(f"tier empty mid-session {key!r}")
            if not members_used or members_used[-1] != member:
                members_used.append(member)
            self.tier.acquire(member)
            try:
                while len(delivered) < total:
                    n = min(chunk, total - len(delivered))
                    toks = self.deliver_fn(member, key, seed,
                                           len(delivered), n)
                    delivered.extend(toks)
            except MemberDown:
                rehashes += 1
                self.rehashes += 1
                continue
            finally:
                self.tier.release(member)
        return {"tokens": delivered, "members": members_used,
                "rehashes": rehashes, "delivered": len(delivered)}
