"""Speculative decoding: prompt-lookup (n-gram) drafting.

Reference context: the reference's engines ship speculative decoding as a
headline feature (SGLang/vLLM n-gram a.k.a. prompt-lookup mode — no draft
model). The TPU-native twist here: the engine's sampling randomness is a
pure function of (request seed, token position) (see sampler.py), so the
verify forward can recompute EXACTLY the token the sequential path would
have sampled at every drafted position. Speculative output is therefore
bit-identical to non-speculative output — for greedy AND temperature
sampling — not merely drawn from the same distribution. No rejection
sampling machinery is needed: accept while draft matches the recomputed
sample, take the recomputed sample at the first mismatch (that token is
the true next token), roll kv_len back past the junk KV.

This module is the host-side drafting half: an incremental n-gram index
over prompt + output per request. The device-side verify lives in
Engine._spec_decode_step (one (B, K+1) forward_paged + per-position
sampling — the same program shape as a prefill chunk).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class NGramIndex:
    """Incremental last-occurrence n-gram index over one token sequence.

    ``draft(k)`` proposes the k tokens that followed the MOST RECENT prior
    occurrence of the current trailing n-gram (prompt-lookup decoding).
    Updates are O(1) per appended token; drafting is O(k)."""

    def __init__(self, n: int = 3):
        if n < 1:
            raise ValueError("ngram n must be >= 1")
        self.n = n
        self.tokens: List[int] = []
        # gram -> index just past its most recent occurrence, and the
        # occurrence before that. The tail's own registration would hide
        # earlier matches in a single-slot map — at draft time the tail
        # IS the most recent occurrence, so the useful one is `_prev`.
        self._last: Dict[Tuple[int, ...], int] = {}
        self._prev: Dict[Tuple[int, ...], int] = {}

    def extend(self, tokens: List[int]) -> None:
        for t in tokens:
            self.append(t)

    def append(self, tok: int) -> None:
        self.tokens.append(tok)
        n = self.n
        if len(self.tokens) >= n:
            gram = tuple(self.tokens[-n:])
            old = self._last.get(gram)
            if old is not None:
                self._prev[gram] = old
            self._last[gram] = len(self.tokens)

    def draft(self, k: int) -> List[int]:
        """Up to k draft tokens continuing the current tail, [] if the
        trailing n-gram has no earlier occurrence."""
        n = self.n
        if k <= 0 or len(self.tokens) < n:
            return []
        gram = tuple(self.tokens[-n:])
        cont = self._last.get(gram)
        if cont is not None and cont >= len(self.tokens):
            cont = self._prev.get(gram)  # most recent non-tail occurrence
        if cont is None:
            return []
        return self.tokens[cont:cont + k]
