"""Engine server process — what a role pod runs.

Reference analog: the SGLang server container in RBG's role templates
(``examples/inference/*.yaml``); here the engine is ours and the rendezvous
contract is the one the control plane injects (RBG_* envs, see
rbg_tpu.discovery.env_builder).

Modes (= PD-disagg roles): ``unified`` serves generate; ``prefill`` answers
prefill ops with KV bundles; ``decode`` accepts bundles and decodes.

Env contract consumed: ``RBG_SERVE_PORT`` (from the executor or the port
allocator's ``RBG_PORT_SERVE``), ``RBG_JAX_NUM_PROCESSES``/``RBG_JAX_PROCESS_ID``/
``RBG_JAX_COORDINATOR_ADDRESS`` (multi-host slice init), ``RBG_TPU_*``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socketserver
import sys
import threading
import time

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.protocol import (CODE_DRAINING, DeadlineExceeded,
                                     Rejected, bundle_from_wire,
                                     bundle_to_wire, recv_msg, send_msg)
from rbg_tpu.obs import names, trace
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_lock


# Blocking decode_stream wait bound when the client sent no deadline —
# the same legacy contract as service.DEFAULT_TIMEOUT_S.
DEFAULT_WAIT_S = 600.0


def _deadline_of(obj: dict):
    """Absolute monotonic deadline from a wire ``timeout_s`` (None = the
    legacy unbounded contract). The router stamps the REMAINING client
    budget here per hop, so engine-side enforcement composes with its."""
    t = obj.get("timeout_s")
    if t is None:
        return None
    t = float(t)
    if t <= 0:
        raise ValueError(f"timeout_s must be > 0, got {t}")
    return time.monotonic() + t


def build_config(args) -> EngineConfig:
    return EngineConfig(
        model=args.model, mode=args.mode, page_size=args.page_size,
        num_pages=args.num_pages, max_batch=args.max_batch,
        max_seq_len=args.max_seq_len, prefill_chunk=args.prefill_chunk,
        use_pallas=args.use_pallas,
        checkpoint_path=args.checkpoint_path,
        kv_dtype=args.kv_dtype,
        multi_step=args.multi_step,
        ragged=args.ragged,
        vocab_size=args.vocab_size,
        speculative=args.speculative,
        spec_k=args.spec_k,
        spec_ngram=args.spec_ngram,
        grammar_table=args.grammar_table,
        grammar_state_budget=args.grammar_state_budget,
        slo_ttft_s=args.slo_ttft_s,
        slo_tpot_s=args.slo_tpot_s,
        host_tier_bytes=args.host_tier_bytes,
        early_reject=args.early_reject,
        early_reject_factor=args.early_reject_factor,
    )


class Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv = self.server
        while True:
            try:
                obj, k, v = recv_msg(self.request)
            except (ConnectionError, json.JSONDecodeError):
                return
            if obj is None:
                return
            try:
                self._dispatch(srv, obj, k, v)
            except ConnectionError:
                return      # client went away; generation already cancelled
            except Exception as e:
                try:
                    send_msg(self.request, {"error": str(e)})
                except OSError:
                    return

    def _stream_pending(self, service, pending, first_tokens=(),
                        with_logprobs=False, deadline=None):
        """Relay a pending generation as incremental token-batch messages:
        ``{"tokens": [...], "done": false}``* then a final ``done`` frame
        with ttft. The transport framing the SSE front end rides on. With
        logprobs, frames carry an aligned ``"logprobs"`` slice (emission
        waits for both lists — the loop thread appends tokens first).
        ``deadline`` (absolute monotonic) caps the relay; the service loop
        aborts the generation itself at the same deadline."""
        import time as _time

        from rbg_tpu.engine.service import DEFAULT_TIMEOUT_S
        try:
            if first_tokens:
                frame = {"tokens": list(first_tokens), "done": False}
                if with_logprobs:
                    # PD first token is sampled prefill-side (no logprob) —
                    # null keeps the 1:1 alignment (OpenAI's convention for
                    # tokens without a logprob).
                    frame["logprobs"] = [None] * len(first_tokens)
                send_msg(self.request, frame)
            sent = 0
            if deadline is None:
                # lint: allow[deadline-hygiene] ingress fallback: the client sent no timeout_s, so THIS is the one stamp the legacy contract gets
                deadline = _time.monotonic() + DEFAULT_TIMEOUT_S
            while True:
                done = pending.done.is_set()
                if done and pending.error:
                    frame = {"error": pending.error, "done": True}
                    if pending.code:
                        frame["code"] = pending.code
                    send_msg(self.request, frame)
                    return
                tokens = list(pending.tokens)
                if with_logprobs:
                    lps = list(pending.logprobs)
                    n = len(tokens) if done else min(len(tokens), len(lps))
                    if n > sent:
                        send_msg(self.request, {"tokens": tokens[sent:n],
                                                "logprobs": lps[sent:n],
                                                "done": False})
                        sent = n
                elif len(tokens) > sent:
                    send_msg(self.request, {"tokens": tokens[sent:],
                                            "done": False})
                    sent = len(tokens)
                if done and sent == len(pending.tokens):
                    break
                if _time.monotonic() > deadline:
                    from rbg_tpu.engine.protocol import CODE_DEADLINE
                    service.cancel(pending)  # recycle slot + pages
                    send_msg(self.request, {"error": "generation timed out",
                                            "code": CODE_DEADLINE,
                                            "done": True})
                    return
                _time.sleep(0.005)
            ttft = (pending.t_first - pending.t_submit) if pending.t_first else 0.0
            send_msg(self.request, {"tokens": [], "done": True, "ttft_s": ttft})
        except (BrokenPipeError, ConnectionResetError, OSError):
            # Client went away mid-stream (e.g. the HTTP edge cut at a stop
            # string): abort the generation so it stops occupying a batch
            # slot and KV pages for the rest of its max_new_tokens budget.
            service.cancel(pending)
            raise ConnectionError("client closed stream")

    _DATA_OPS = frozenset({"generate", "generate_text", "embed",
                           "prefill", "decode_bundle", "kv_stream",
                           "decode_stream"})

    def _dispatch(self, srv, obj, k, v):
        op = obj.get("op")
        if op == "health":
            ready = srv.service is not None or srv.prefill is not None or srv.decode is not None
            resp = {"ok": ready, "mode": srv.mode, "draining": srv.draining}
            if srv.draining:
                resp["draining_for_s"] = round(
                    time.monotonic() - srv.drain_started, 3)
            send_msg(self.request, resp)
            return
        if srv.draining and op in self._DATA_OPS:
            # Drain contract: in-flight work finishes, NEW work is refused
            # with a structured code the router treats as
            # route-around-without-evicting. "done" terminates stream
            # clients that won't look past the first frame. The
            # retry_after_s hint is the remaining drain budget (capped):
            # by then either the replacement serves or this address is
            # gone — under a ROLLING drain the router surfaces the fleet's
            # smallest hint to the client.
            REGISTRY.inc(names.SERVING_DRAIN_REFUSALS_TOTAL)
            budget = getattr(srv, "drain_deadline_s", 30.0)
            remaining = max(0.0, budget - (time.monotonic()
                                           - srv.drain_started))
            send_msg(self.request, {
                "error": "server is draining (SIGTERM received)",
                "code": CODE_DRAINING, "done": True,
                "retry_after_s": round(min(5.0, max(0.5, remaining)), 3)})
            return
        if op in self._DATA_OPS:
            srv.note_inflight(+1)
            try:
                self._dispatch_data(srv, obj, k, v)
            finally:
                srv.note_inflight(-1)
            return
        self._dispatch_data(srv, obj, k, v)

    def _dispatch_data(self, srv, obj, k, v):
        """Auth gate + trace wrapper around :meth:`_serve_data`: every data
        op continues the request's wire trace context (or starts one when
        this server IS the ingress) as an ``engine.op`` span, ambient for
        the op's duration so the service queue/scan spans and the PD
        KV-handoff span parent under it."""
        op = obj.get("op")
        if srv.auth_token and op not in ("metrics", "slo"):
            # Data-plane token gate (VERDICT r4 #6): prefill/decode_bundle
            # carry KV activations, generate carries prompts — none of it
            # for unauthenticated peers. health (above) stays open for
            # probes; metrics too (scrape-friendly, numbers only).
            from rbg_tpu.engine.protocol import token_ok
            if not token_ok(obj.get("token"), srv.auth_token):
                send_msg(self.request, {"error": "unauthorized"})
                return
        if op == "slo":
            # Operator pull of SLO attainment + windowed signals (the
            # serving-plane sibling of the admin `slo` op; numbers only,
            # so it stays scrape-open like `metrics`). Same clamped-
            # response contract as `traces`.
            from rbg_tpu.obs.slo import slo_response
            send_msg(self.request, slo_response(obj.get("window")))
            return
        if op == "traces":
            # Operator pull of the trace sink (the serving-plane sibling of
            # the admin `traces` op): recent + slowest ring buffers, the
            # slowest request's waterfall, and the histogram exemplars
            # linking a bad quantile to a trace_id.
            from rbg_tpu.obs.trace import traces_response
            send_msg(self.request, traces_response(obj.get("n", 10)))
            return
        if op in self._DATA_OPS:
            span = trace.from_wire(obj.get("trace"), names.SPAN_ENGINE_OP,
                                   op=op, mode=srv.mode)
            if not span:
                return self._serve_data(srv, obj, k, v)
            try:
                with trace.use_span(span):
                    return self._serve_data(srv, obj, k, v)
            finally:
                span.end()
        return self._serve_data(srv, obj, k, v)

    def _serve_data(self, srv, obj, k, v):
        op = obj.get("op")
        if op == "warmup":
            # Compile every jit bucket variant NOW (one blocking op per
            # serving pod, before it takes traffic) instead of stalling
            # live requests at first variant hit. The serving-SLO analog
            # of the control plane's warmup pods (SURVEY #9).
            import time as _time
            t0 = _time.perf_counter()
            n = int(obj.get("input_len", 32))
            if srv.service is not None:
                srv.service.warmup(n)
            elif srv.prefill is not None:
                with srv.pd_lock:
                    srv.prefill.warmup(n)
            elif srv.decode is not None:
                srv.decode.warmup(n)
            else:
                send_msg(self.request, {"error": "engine not ready"})
                return
            send_msg(self.request, {
                "ok": True,
                "elapsed_s": round(_time.perf_counter() - t0, 2)})
            return
        if op == "metrics":
            stats = {}
            eng = None
            if srv.service is not None:
                stats = srv.service.stats()
                eng = srv.service.engine
            elif srv.prefill is not None:
                stats = {**srv.prefill.engine.metrics, **srv.prefill.metrics}
                eng = srv.prefill.engine
            elif srv.decode is not None:
                eng = srv.decode.engine
                stats = {**eng.metrics, **srv.decode.worker.metrics,
                         **srv.decode.service_stats(),
                         "running": len(eng.running),
                         "waiting": len(eng.waiting),
                         "free_pages": eng.allocator.free_pages}
            if eng is not None and getattr(eng, "host_tier", None) is not None:
                stats["host_tier"] = eng.host_tier.stats()
                stats["device_tier_pages"] = (
                    eng.radix.cached_pages if eng.radix is not None else 0)
            stats["draining"] = srv.draining
            send_msg(self.request, {"metrics": stats, "mode": srv.mode})
            return
        if op == "generate_text" and srv.service is not None:
            tok = srv.tokenizer
            vocab = srv.service.engine.mcfg.vocab_size
            if tok.vocab_size > vocab:
                send_msg(self.request, {"error": (
                    f"tokenizer vocab {tok.vocab_size} exceeds model vocab "
                    f"{vocab}; pass --tokenizer-path matching the model")})
                return
            try:
                sampling = SamplingParams.from_wire(
                    obj, default_max_tokens=64, stop_token=tok.eos_id)
                deadline = _deadline_of(obj)
            except (ValueError, TypeError) as e:
                send_msg(self.request, {"error": f"bad sampling params: {e}"})
                return
            prompt_ids = tok.encode(obj["text"])
            limit = srv.service.engine.cfg.max_seq_len
            if len(prompt_ids) + sampling.max_new_tokens > limit:
                send_msg(self.request, {"error": (
                    f"prompt ({len(prompt_ids)} tokens) + max_new_tokens "
                    f"({sampling.max_new_tokens}) exceeds max_seq_len {limit}")})
                return
            try:
                ids, ttft = srv.service.submit(prompt_ids, sampling,
                                               deadline=deadline)
            except Rejected as e:
                send_msg(self.request, e.to_wire())
                return
            send_msg(self.request, {"text": tok.decode(ids), "tokens": ids,
                                    "ttft_s": ttft})
            return
        if op == "generate" and srv.service is not None:
            try:
                sampling = SamplingParams.from_wire(obj)
                deadline = _deadline_of(obj)
            except (ValueError, TypeError) as e:
                send_msg(self.request, {"error": f"bad sampling params: {e}"})
                return
            if obj.get("stream"):
                try:
                    pending = srv.service.submit_async(obj["prompt"], sampling,
                                                       deadline=deadline)
                except Rejected as e:
                    send_msg(self.request, {**e.to_wire(), "done": True})
                    return
                self._stream_pending(srv.service, pending,
                                     with_logprobs=sampling.logprobs,
                                     deadline=deadline)
                return
            try:
                p = srv.service.submit_wait(obj["prompt"], sampling,
                                            deadline=deadline)
            except Rejected as e:
                send_msg(self.request, e.to_wire())
                return
            except (TimeoutError, ValueError) as e:
                send_msg(self.request, {"error": str(e)})
                return
            resp = {"tokens": p.tokens, "ttft_s": srv.service.ttft(p)}
            if sampling.logprobs:
                resp["logprobs"] = p.logprobs
            send_msg(self.request, resp)
            return
        if op == "embed":
            # Any engine mode serves embeddings — prefill/decode roles hold
            # the same weights, so a PD group's edge works too.
            eng = None
            if srv.service is not None:
                eng = srv.service.engine
            elif srv.prefill is not None:
                eng = srv.prefill.engine
            elif srv.decode is not None:
                eng = srv.decode.engine
            if eng is None:
                send_msg(self.request, {"error": "engine not ready"})
                return
            tok = srv.tokenizer
            if "prompts" in obj:
                prompts = [list(p) for p in obj["prompts"]]
            elif "text" in obj:
                prompts = [tok.encode(obj["text"], add_bos=False)]
            else:
                prompts = [list(obj.get("prompt") or [])]
            from rbg_tpu.engine.service import embed_prompts
            try:
                vecs = embed_prompts(eng, prompts)
            except ValueError as e:
                send_msg(self.request, {"error": str(e)})
                return
            send_msg(self.request, {
                "embeddings": vecs, "dim": len(vecs[0]),
                "prompt_tokens": sum(len(p) for p in prompts),
                # single-prompt back-compat field
                "embedding": vecs[0]})
            return
        if op == "prefill" and srv.prefill is not None:
            try:
                sampling = SamplingParams.from_wire(obj)
                deadline = _deadline_of(obj)
            except (ValueError, TypeError) as e:
                send_msg(self.request, {"error": f"bad sampling params: {e}"})
                return
            # The prefill engine serializes behind pd_lock: a deadline-
            # carrying request bounds its wait for the lock (the implicit
            # queue here), and a budget spent while queued is refused
            # BEFORE any prefill compute burns chip time.
            qspan = trace.child(names.SPAN_SERVICE_QUEUE_WAIT)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not srv.pd_lock.acquire(timeout=remaining):
                    REGISTRY.inc(names.SERVING_DEADLINE_EXCEEDED_TOTAL,
                                 stage="prefill_queue")
                    qspan.end(outcome="deadline")
                    send_msg(self.request, DeadlineExceeded(
                        "deadline spent waiting for the prefill engine"
                    ).to_wire())
                    return
            else:
                srv.pd_lock.acquire()
            qspan.end(outcome="admitted")
            t_lock = time.perf_counter()
            pspan = trace.child(names.SPAN_PD_PREFILL,
                                prompt_tokens=len(obj.get("prompt") or ()))
            push_to = obj.get("push_to")
            push = None
            try:
                if push_to and srv.kv_push is not None:
                    # KVCache-centric path: chunks stream DIRECTLY to the
                    # decode peer as prefill chunks complete; the sends
                    # ride a sender thread, so the pd_lock critical
                    # section covers compute only, never the link.
                    push = srv.prefill.prefill_stream(
                        obj["prompt"], sampling, transport=srv.kv_push,
                        peer=push_to,
                        stream_id=obj.get("stream_id"),
                        deadline=deadline)
                else:
                    bundle = srv.prefill.prefill(obj["prompt"], sampling,
                                                 deadline=deadline)
            except DeadlineExceeded as e:
                pspan.end(outcome="deadline_abort")
                send_msg(self.request, e.to_wire())
                return
            except Exception:
                pspan.end(outcome="error")
                raise
            finally:
                srv.pd_lock.release()
                REGISTRY.observe(names.PD_LOCK_HOLD_SECONDS,
                                 time.perf_counter() - t_lock,
                                 lock="server_pd")
            if push is not None:
                pspan.end(outcome="pushed", bytes=push.meta.nbytes())
                # Reply the moment COMPUTE is done — the chunk tail drains
                # to the decode peer while the router sets up the decode
                # leg. An already-failed push (connect refused surfaces
                # during compute) is reported so the router falls back to
                # the bundle path instead of a doomed decode_stream.
                send_msg(self.request, {
                    "pushed": push.error() is None,
                    "stream_id": push.stream_id,
                    "first_token": push.first_token,
                    "prompt": list(obj["prompt"]),
                    "kv_bytes": push.meta.nbytes(),
                    "push_error": push.error(),
                    # Measured prefill→decode link rates from COMPLETED
                    # pushes — the router folds them into its
                    # transfer-cost-aware decode scoring.
                    "link_rates": srv.kv_push.stats.snapshot()})
                return
            pspan.end(outcome="ok", bytes=bundle.nbytes)
            header, kb, vb = bundle_to_wire(bundle)
            send_msg(self.request, header, kb, vb)
            return
        if op == "kv_stream" and srv.decode is not None:
            self._serve_kv_stream(srv, obj)
            return
        if op == "decode_stream" and srv.decode is not None:
            self._serve_decode_stream(srv, obj)
            return
        if op == "decode_bundle" and srv.decode is not None:
            bundle = bundle_from_wire(obj, k, v)
            try:
                sampling = SamplingParams.from_wire(obj)
                deadline = _deadline_of(obj)
            except (ValueError, TypeError) as e:
                send_msg(self.request, {"error": f"bad sampling params: {e}"})
                return
            # Continuous batching: bundles from concurrent connections decode
            # together on the device (no per-connection serialization).
            if obj.get("stream"):
                # A bundle finished at inject (max_new_tokens == 1 / stop
                # token) resolves with done set and no tokens — the stream
                # then carries only the first_token frame.
                try:
                    pending = srv.decode.submit_async(bundle, sampling,
                                                      deadline=deadline)
                except Rejected as e:
                    send_msg(self.request, {**e.to_wire(), "done": True})
                    return
                self._stream_pending(srv.decode, pending,
                                     first_tokens=[bundle.first_token],
                                     with_logprobs=sampling.logprobs,
                                     deadline=deadline)
                return
            try:
                p = srv.decode.submit_wait(bundle, sampling,
                                           deadline=deadline)
            except Rejected as e:
                send_msg(self.request, e.to_wire())
                return
            except (TimeoutError, ValueError) as e:
                send_msg(self.request, {"error": str(e)})
                return
            resp = {"tokens": [bundle.first_token] + p.tokens}
            if sampling.logprobs:
                # First token sampled prefill-side — null placeholder.
                resp["logprobs"] = [None] + p.logprobs
            send_msg(self.request, resp)
            return
        send_msg(self.request, {"error": f"unsupported op {op!r} in mode {srv.mode}"})

    def _serve_kv_stream(self, srv, obj):
        """Ingest one inbound KV chunk stream on THIS connection (the
        prefill peer opened it): frames land in the decode service's
        stream registry; the loop thread commits them into the page table
        as they arrive. Replies an ack after FIN (the sender's drain
        barrier). A broken connection fails the stream with a structured
        error — never a wedge."""
        from rbg_tpu.kvtransfer.chunks import StreamFin
        from rbg_tpu.kvtransfer.transport import frame_from_wire

        sid = obj.get("stream_id") or ""
        rx = srv.decode.kv_streams.get_or_create(sid)
        srv.decode.watch_stream(rx)
        nbytes = 0
        while True:
            try:
                fobj, fk, fv = recv_msg(self.request)
            except (ConnectionError, json.JSONDecodeError) as e:
                rx.fail(f"kv stream connection broke: {e}")
                return
            if fobj is None:
                rx.fail("kv stream EOF before FIN")
                return
            try:
                frame = frame_from_wire(fobj, fk, fv)
            except Exception as e:  # noqa: BLE001 — fail the stream, not the handler
                rx.fail(f"bad kv frame: {e}")
                send_msg(self.request, {"error": str(e)})
                return
            nbytes += len(fk or b"") + len(fv or b"")
            rx.feed(frame)
            if isinstance(frame, StreamFin):
                REGISTRY.inc(names.KVT_BYTES_TOTAL, float(nbytes),
                             direction="recv", transport="tcp")
                send_msg(self.request, {"ok": True, "bytes": nbytes})
                return

    def _serve_decode_stream(self, srv, obj):
        """Decode a previously (or concurrently) pushed KV stream: wait
        for admission coverage, then decode exactly like decode_bundle.
        The row is admitted the moment layer coverage for the prompt is
        complete — the stream's FIN may still be in flight."""
        from rbg_tpu.engine.protocol import CODE_KV_STREAM
        from rbg_tpu.kvtransfer.chunks import StreamError

        try:
            sampling = SamplingParams.from_wire(obj)
            deadline = _deadline_of(obj)
        except (ValueError, TypeError) as e:
            send_msg(self.request, {"error": f"bad sampling params: {e}"})
            return
        sid = obj.get("stream_id") or ""
        rx = srv.decode.kv_streams.get_or_create(sid)
        srv.decode.watch_stream(rx)
        wait_s = 30.0
        if deadline is not None:
            wait_s = max(0.0, min(wait_s, deadline - time.monotonic()))
        try:
            rx.wait_ready(wait_s)
        except StreamError as e:
            # Mark the receiver failed so the loop thread's pump releases
            # any pages it pre-allocated — an abandoned stream must not
            # hold KV capacity.
            rx.fail(f"abandoned: {e}")
            srv.decode.kv_streams.pop(sid)
            send_msg(self.request, {"error": f"kv stream: {e}",
                                    "code": CODE_KV_STREAM, "done": True})
            return
        first_token = rx.assembler.first_token
        if obj.get("stream"):
            try:
                pending = srv.decode.submit_stream(rx, sampling,
                                                   deadline=deadline)
            except Rejected as e:
                send_msg(self.request, {**e.to_wire(), "done": True})
                return
            self._stream_pending(srv.decode, pending,
                                 first_tokens=[first_token],
                                 with_logprobs=sampling.logprobs,
                                 deadline=deadline)
            return
        p = None
        try:
            p = srv.decode.submit_stream(rx, sampling, deadline=deadline)
            srv.decode.wait(p, DEFAULT_WAIT_S if deadline is None
                            else max(0.0, deadline - time.monotonic()) + 1.0)
        except Rejected as e:
            send_msg(self.request, e.to_wire())
            return
        except (TimeoutError, ValueError) as e:
            frame = {"error": str(e)}
            # Admit-time stream failures (dead kv_stream connection,
            # no pages for the pushed KV) keep their wire code so the
            # router re-routes in bundle mode instead of surfacing them.
            if p is not None and p.code:
                frame["code"] = p.code
            send_msg(self.request, frame)
            return
        resp = {"tokens": [first_token] + p.tokens}
        if sampling.logprobs:
            resp["logprobs"] = [None] + p.logprobs
        send_msg(self.request, resp)
        return


class EngineServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def note_inflight(self, delta: int) -> None:
        with self._inflight_lock:
            self._inflight += delta

    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight


def start_drain(server: EngineServer, drain_deadline_s: float) -> None:
    """Flip the server into draining and schedule the clean exit.

    The state machine (reference: RBG's group-level drain contract —
    ANN_DRAIN_DEADLINE / PreparingDelete, api/constants.py): serving →
    (SIGTERM) → draining — health reports it, every NEW data op is refused
    with code "draining", in-flight requests keep running — → all in-flight
    done OR drain deadline passed → listener shutdown → process exit 0.
    Idempotent: a second SIGTERM neither resets the clock nor stacks
    drainer threads."""
    if server.draining:
        return
    server.draining = True
    server.drain_started = time.monotonic()
    REGISTRY.inc(names.SERVING_DRAINS_TOTAL)
    REGISTRY.set_gauge(names.SERVING_DRAINING, 1.0)
    # A draining prefill replica's prefix-directory entries go stale the
    # moment it exits — invalidate them NOW so no router routes a prefix
    # hit at a pod that is about to refuse it.
    pf = server.prefill
    if pf is not None and pf.directory is not None and pf.advertise_addr:
        try:
            pf.directory.invalidate_backend(pf.advertise_addr,
                                            reason="drain")
        except Exception:  # noqa: BLE001 — drain must never fail on this
            pass
    print(f"draining: finishing in-flight work "
          f"(deadline {drain_deadline_s:.1f}s)", flush=True)

    def drainer():
        deadline = server.drain_started + drain_deadline_s
        while time.monotonic() < deadline:
            busy = server.inflight() > 0
            for s in (server.service, server.decode):
                if s is not None and (s.engine.has_work() or s._queue):
                    busy = True
            if not busy:
                break
            time.sleep(0.05)
        drained = time.monotonic() - server.drain_started
        aborted = server.inflight()
        print(f"drain {'complete' if not aborted else 'deadline'} after "
              f"{drained:.2f}s ({aborted} in-flight aborted)", flush=True)
        server.shutdown()

    threading.Thread(target=drainer, daemon=True, name="drainer").start()


def serve(args) -> None:
    cfg = build_config(args)
    cfg.validate()  # fail fast on bad CLI values, before the port binds
    port = int(os.environ.get("RBG_SERVE_PORT")
               or os.environ.get("RBG_PORT_SERVE")
               or args.port)

    # Multi-host slice init (the control plane injected the contract).
    nproc = int(os.environ.get("RBG_JAX_NUM_PROCESSES", "1"))
    if nproc > 1 and os.environ.get("RBG_DISTRIBUTED", "0") == "1":
        import jax
        jax.distributed.initialize(
            os.environ["RBG_JAX_COORDINATOR_ADDRESS"],
            num_processes=nproc,
            process_id=int(os.environ["RBG_JAX_PROCESS_ID"]),
        )

    # Windowed-signal sampler (obs/timeseries.py): the `slo` data op and
    # `rbg-tpu top` read rates/means over its ring buffer — start it with
    # the process so the first operator pull already has history.
    from rbg_tpu.obs import timeseries
    timeseries.ensure_started()

    server = EngineServer(("127.0.0.1", port), Handler)
    server.mode = cfg.mode
    server.service = server.prefill = server.decode = None
    server.auth_token = (args.auth_token
                         or os.environ.get("RBG_DATA_TOKEN") or None)
    server.pd_lock = named_lock("engine.server_pd")
    server.kv_push = None          # TCPTransport, prefill mode only
    server.draining = False
    server.drain_started = 0.0
    server._inflight = 0
    server._inflight_lock = named_lock("engine.server_inflight")
    max_queue = args.max_queue if args.max_queue > 0 else None
    drain_deadline_s = float(
        args.drain_deadline_s
        if args.drain_deadline_s is not None
        else os.environ.get("RBG_DRAIN_DEADLINE_S", "30"))
    server.drain_deadline_s = drain_deadline_s
    # SIGTERM = the rollout/scale-down signal (what the executor and k8s
    # send): graceful drain instead of dropping in-flight streams on the
    # floor. serve() runs on the main thread, where signal() is legal.
    try:
        signal.signal(signal.SIGTERM,
                      lambda *_: start_drain(server, drain_deadline_s))
    except ValueError:
        pass  # non-main-thread embedding (tests) — drain via start_drain()
    from rbg_tpu.engine.tokenizer import ByteTokenizer
    server.tokenizer = ByteTokenizer()  # replaced by init_engine if HF given

    # Bind the port FIRST (readiness probes connect), then load model and
    # tokenizer in the background — a slow HF load must not stall accepts.
    def init_engine():
        try:
            if args.tokenizer_path:
                from rbg_tpu.engine.tokenizer import load_tokenizer
                server.tokenizer = load_tokenizer(args.tokenizer_path)
            def load_adapters(engine):
                import numpy as np
                for spec in args.lora:
                    name, _, path = spec.partition("=")
                    if not path:
                        raise ValueError(f"--lora expects NAME=PATH, got "
                                         f"{spec!r}")
                    z = np.load(path)
                    targets = sorted({k.rsplit(".", 1)[0] for k in z.files
                                      if k.endswith(".A")})
                    adapter = {t: (z[f"{t}.A"], z[f"{t}.B"])
                               for t in targets}
                    alpha = float(z["alpha"]) if "alpha" in z.files else 16.0
                    engine.load_lora(name, adapter, alpha=alpha)

            # Fully wire each engine (grammar table, adapters) BEFORE
            # publishing it on the server object: health reports ready the
            # moment the attribute is set, and a json_mode request racing
            # the grammar wiring used to get a spurious admission error.
            if cfg.mode == "prefill":
                from rbg_tpu.engine.pd import PrefillWorker
                pool = None
                directory = None
                pool_addr = args.kv_pool or os.environ.get(
                    "RBG_KV_POOL_ADDR", "")
                if pool_addr:
                    from rbg_tpu.engine.kvpool import KVPoolClient
                    pool = KVPoolClient(
                        pool_addr,
                        token=server.auth_token,
                        ca_path=(args.kv_pool_ca
                                 or os.environ.get("RBG_KV_POOL_CA")
                                 or None))
                    # The pool server hosts the cluster prefix directory
                    # (dir_* ops): computed prefixes register under this
                    # replica's serving address so the router can steer
                    # prefix-sharing requests to ANY holder.
                    from rbg_tpu.kvtransfer.directory import DirectoryClient
                    directory = DirectoryClient(
                        pool_addr, token=server.auth_token,
                        page_size=cfg.page_size)
                advertise = (args.advertise_addr
                             or os.environ.get("RBG_ADVERTISE_ADDR")
                             or f"127.0.0.1:{port}")
                prefill = PrefillWorker(cfg, pool=pool,
                                        directory=directory,
                                        advertise_addr=advertise)
                if prefill.engine.host_tier is not None and directory:
                    # Host-tier spills register in the cluster directory
                    # under this replica's serving address (tier="host"),
                    # so the router's tier-fetch-cost scoring sees them.
                    prefill.engine.host_tier.wire_directory(
                        directory, advertise)
                prefill.engine.enable_json_grammar(server.tokenizer)
                load_adapters(prefill.engine)
                if args.kv_stream != "off":
                    from rbg_tpu.kvtransfer.transport import TCPTransport
                    server.kv_push = TCPTransport(token=server.auth_token)
                server.prefill = prefill
            elif cfg.mode == "decode":
                from rbg_tpu.engine.service import DecodeService
                decode = DecodeService(cfg, max_queue=max_queue)
                decode.engine.enable_json_grammar(server.tokenizer)
                load_adapters(decode.engine)
                server.decode = decode
            else:
                from rbg_tpu.engine.service import EngineService
                service = EngineService(cfg, max_queue=max_queue)
                service.engine.enable_json_grammar(server.tokenizer)
                load_adapters(service.engine)
                server.service = service
        except Exception:
            # A pod that cannot build its engine must CRASH (so the restart
            # policy sees it), not linger as a never-ready zombie listener.
            import traceback
            traceback.print_exc()
            os._exit(1)
        print(f"engine ready mode={cfg.mode} model={cfg.model} port={port}",
              flush=True)

    threading.Thread(target=init_engine, daemon=True).start()
    print(f"engine listening on 127.0.0.1:{port}", flush=True)
    server.serve_forever()
    # serve_forever returns only via the drainer's shutdown(): close the
    # listener and fall out of main() with exit code 0 — a clean rollout.
    server.server_close()
    print("engine exited cleanly after drain", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rbg-tpu-engine")
    ap.add_argument("--model", default=os.environ.get("RBG_MODEL", "tiny"))
    ap.add_argument("--mode", default="unified",
                    choices=["unified", "prefill", "decode"])
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=512)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-seq-len", type=int, default=1024)
    ap.add_argument("--prefill-chunk", type=int, default=64)
    ap.add_argument("--use-pallas", default="auto")
    ap.add_argument("--kv-dtype", default="model", choices=["model", "int8"],
                    help="int8 halves KV HBM (unified mode only)")
    ap.add_argument("--checkpoint-path",
                    default=os.environ.get("RBG_CHECKPOINT_PATH", ""),
                    help="orbax dir or local HF dir (else random init)")
    ap.add_argument("--tokenizer-path",
                    default=os.environ.get("RBG_TOKENIZER_PATH", ""),
                    help="local HF tokenizer dir (else byte-level fallback)")
    ap.add_argument("--kv-pool",
                    default=os.environ.get("RBG_KV_POOL_ADDR", ""),
                    help="host:port of the shared KV pool (prefill mode; "
                         "Mooncake-store analog, rbg_tpu.engine.kvpool)")
    ap.add_argument("--kv-pool-ca", default="",
                    help="CA cert path for a TLS kv-pool (default: "
                         "$RBG_KV_POOL_CA; empty = plaintext)")
    ap.add_argument("--kv-stream", choices=("auto", "off"), default="auto",
                    help="chunked layer-overlapped prefill→decode KV "
                         "streaming (the router passes push_to and this "
                         "prefill pushes chunks as they compute); 'off' "
                         "keeps the whole-bundle wire path")
    ap.add_argument("--advertise-addr", default="",
                    help="address this replica registers in the cluster "
                         "prefix directory (default: $RBG_ADVERTISE_ADDR "
                         "or 127.0.0.1:<port>)")
    ap.add_argument("--auth-token", default="",
                    help="require this bearer token on every data op "
                         "(default: $RBG_DATA_TOKEN; empty = open wire). "
                         "The same token authenticates this server's own "
                         "kv-pool client calls.")
    ap.add_argument("--multi-step", type=int, default=1,
                    help="decode steps fused per device dispatch (lax.scan "
                         "window; higher = throughput, burstier streaming)")
    ap.add_argument("--ragged", choices=("auto", "off"), default="auto",
                    help="ragged unified prefill/decode dispatch "
                         "(continuous batching); 'off' keeps the split "
                         "phase paths — the bit-identical baseline")
    ap.add_argument("--lora", action="append", default=[],
                    metavar="NAME=PATH.npz",
                    help="load a LoRA adapter (repeatable). The npz holds "
                         "'{target}.A' [L,d,r] / '{target}.B' [L,r,o] "
                         "arrays (targets wq/wk/wv/wo/w_gate/w_up/w_down) "
                         "and optional scalar 'alpha'")
    ap.add_argument("--vocab-size", type=int, default=0,
                    help="override the preset's vocab size (0 = keep; lets "
                         "demo models cover the byte tokenizer's 259 ids)")
    ap.add_argument("--speculative", choices=("off", "ngram"), default="off",
                    help="prompt-lookup speculative decoding (bit-identical "
                         "output; wins on repetitive/structured text)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="max drafted tokens per speculative verify step")
    ap.add_argument("--spec-ngram", type=int, default=3,
                    help="trailing n-gram length for prompt lookup")
    ap.add_argument("--grammar-table", choices=("auto", "off"),
                    default="auto",
                    help="device-resident grammar tables: constrained "
                         "(regex/json_schema) rows decode inside the fused "
                         "multi-step window; 'off' keeps the host-synced "
                         "per-token mask path")
    ap.add_argument("--grammar-state-budget", type=int, default=512,
                    help="max token-level automaton states per grammar "
                         "table (S x V x 5 bytes each); grammars over "
                         "budget fall back to the host-synced path")
    ap.add_argument("--slo-ttft-s", type=float, default=2.0,
                    help="per-request TTFT target the serving loop judges "
                         "every finished request against (rbg_slo_* "
                         "attainment/goodput series; 0 disables the "
                         "dimension)")
    ap.add_argument("--slo-tpot-s", type=float, default=0.5,
                    help="per-output-token latency target (time per token "
                         "after the first; 0 disables the dimension)")
    ap.add_argument("--host-tier-bytes", type=int, default=0,
                    help="host-DRAM KV spill tier budget in bytes: device "
                         "page-pool evictions spill prefix pages here and "
                         "admission promotes them back on a hit (0 = off; "
                         "needs the radix cache; Mooncake's 'more storage "
                         "for less computation' level)")
    ap.add_argument("--early-reject", choices=("off", "auto"),
                    default="off",
                    help="predictive early rejection: admission predicts "
                         "TTFT (measured queue wait + prefill net of the "
                         "prefix hit this request would get) and sheds at "
                         "ingress with retry_after_s when it exceeds "
                         "--early-reject-factor x --slo-ttft-s")
    ap.add_argument("--early-reject-factor", type=float, default=1.5,
                    help="early-rejection gate as a multiple of the TTFT "
                         "SLO target")
    ap.add_argument("--max-queue", type=int, default=256,
                    help="admission-control bound on the service queue: "
                         "submissions past it are shed with a structured "
                         "'overloaded' error + retry_after_s hint instead "
                         "of queueing unboundedly (0 = unbounded)")
    ap.add_argument("--drain-deadline-s", type=float, default=None,
                    help="graceful-drain budget after SIGTERM: in-flight "
                         "requests may finish for this long before the "
                         "process exits (default: $RBG_DRAIN_DEADLINE_S "
                         "or 30)")
    args = ap.parse_args(argv)
    serve(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
