"""Paged KV cache: device-side page pool + host-side page allocator.

The serving engine's memory system (SGLang/vLLM-equivalent, see PAPERS.md
"Ragged Paged Attention" for the TPU kernel this layout feeds):

* Device: ``k_pages/v_pages [L, num_pages, page_size, KV, hd]`` — one shared
  pool for all sequences, static shapes (XLA-friendly).
* Host: ``PageAllocator`` free list + per-sequence page tables (plain ints —
  page logistics never enter the compiled graph; only gather/scatter indices
  do).

Sharding: pages shard over ``tp`` on the KV-head dim like the contiguous
cache (see rbg_tpu.parallel.sharding.cache_specs).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rbg_tpu.models.config import ModelConfig


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """k/v pages [L, NP, page, KV, hd]. With int8 quantization the pages are
    int8 and per-(slot, head) scales live alongside ([L, NP, page, KV, 1]) —
    halving KV HBM at a small accuracy cost (per-vector absmax scaling)."""

    k_pages: jnp.ndarray
    v_pages: jnp.ndarray
    k_scales: Optional[jnp.ndarray] = None
    v_scales: Optional[jnp.ndarray] = None

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[2]

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scales is not None

    @staticmethod
    def create(cfg: ModelConfig, num_pages: int, page_size: int = 16,
               dtype=None, quantize: bool = False) -> "PagedKVCache":
        if cfg.mla:
            # MLA latent pool: k holds the compressed latent, v the shared
            # RoPE key — ~an order of magnitude less HBM than per-head KV.
            # int8 halves it again: per-token absmax over the latent/rope
            # vector (the write path quantizes generically — the latent is
            # just a 1-head "KV" with dc/dr channel dims).
            kshape = (cfg.num_layers, num_pages, page_size, 1,
                      cfg.kv_lora_rank)
            vshape = (cfg.num_layers, num_pages, page_size, 1,
                      cfg.qk_rope_head_dim)
            if quantize:
                sshape = kshape[:-1] + (1,)
                return PagedKVCache(
                    k_pages=jnp.zeros(kshape, jnp.int8),
                    v_pages=jnp.zeros(vshape, jnp.int8),
                    k_scales=jnp.zeros(sshape, jnp.float32),
                    v_scales=jnp.zeros(sshape, jnp.float32),
                )
            dtype = dtype or cfg.jax_dtype
            return PagedKVCache(k_pages=jnp.zeros(kshape, dtype),
                                v_pages=jnp.zeros(vshape, dtype))
        shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim_)
        if quantize:
            sshape = shape[:-1] + (1,)
            return PagedKVCache(
                k_pages=jnp.zeros(shape, jnp.int8),
                v_pages=jnp.zeros(shape, jnp.int8),
                k_scales=jnp.zeros(sshape, jnp.float32),
                v_scales=jnp.zeros(sshape, jnp.float32),
            )
        dtype = dtype or cfg.jax_dtype
        return PagedKVCache(k_pages=jnp.zeros(shape, dtype),
                            v_pages=jnp.zeros(shape, dtype))

    @staticmethod
    def hbm_bytes(cfg: ModelConfig, num_pages: int, page_size: int = 16,
                  dtype_bytes: int = 2) -> int:
        if cfg.mla:
            per_tok = cfg.kv_lora_rank + cfg.qk_rope_head_dim
            return (cfg.num_layers * num_pages * page_size * per_tok
                    * dtype_bytes)
        return (2 * cfg.num_layers * num_pages * page_size
                * cfg.num_kv_heads * cfg.head_dim_ * dtype_bytes)


class PageAllocator:
    """Host-side page free list with reference counting (shared prefix pages
    from the radix cache hold refcount > 1; copy-on-write is avoided by only
    sharing fully-frozen pages)."""

    def __init__(self, num_pages: int):
        # page 0 is reserved as the null page (padding rows in page tables
        # point at it; their slots are masked out by seq_lens anyway).
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs = np.zeros(num_pages, np.int32)
        self._refs[0] = 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate n pages or None (caller evicts/preempts and retries)."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def share(self, pages: List[int]) -> None:
        for p in pages:
            assert self._refs[p] > 0, f"share of free page {p}"
            self._refs[p] += 1

    def release(self, pages: List[int]) -> None:
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)
            assert self._refs[p] >= 0, f"double free of page {p}"

    def refcount(self, p: int) -> int:
        """Current reference count of one page (the host-tier spill hook
        reads it: a page another holder still pins must not spill — it
        stays device-resident)."""
        return int(self._refs[p])


def pages_for_tokens(n_tokens: int, page_size: int) -> int:
    return (n_tokens + page_size - 1) // page_size
