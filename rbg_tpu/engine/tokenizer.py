"""Tokenizers: HF (local dir) + a zero-dependency byte-level fallback.

The environment is zero-egress, so nothing downloads: ``load_tokenizer``
uses a local HF tokenizer dir when given (via ``transformers``), else the
byte fallback (any model with vocab ≥ 259 can serve text demos with it).
"""

from __future__ import annotations

import os
from typing import List, Optional


class ByteTokenizer:
    """UTF-8 bytes + BOS/EOS. ids: 0..255 bytes, 256 BOS, 257 EOS, 258 PAD."""

    bos_id = 256
    eos_id = 257
    pad_id = 258
    vocab_size = 259

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: List[dict]) -> Optional[str]:
        """Render messages with the model's own chat template (returns None
        when the tokenizer ships no template — caller falls back to the
        plain role-tagged form)."""
        if not getattr(self._tok, "chat_template", None):
            return None
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True)


class IncrementalDetokenizer:
    """Streaming token→text decoding that never emits half a character.

    Feed token ids as they arrive; ``feed`` returns the newly-safe text
    delta. A decode ending in U+FFFD (replacement char) is held back — the
    token that completes the multi-byte sequence (or multi-token grapheme,
    for HF BPE) will release it. ``flush`` force-emits the remainder.

    The concatenation of all deltas equals ``tokenizer.decode(all_ids)``
    exactly (modulo a trailing U+FFFD only when the stream itself ends
    mid-character)."""

    # Tail tokens kept as decode context after a commit; commits trigger at
    # twice this. Bounds per-feed work to O(window) instead of re-decoding
    # the whole stream (O(n²) over a long completion).
    WINDOW = 16

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._tail: List[int] = []   # un-committed trailing ids
        self._emitted = 0            # chars of decode(self._tail) emitted

    def feed(self, ids) -> str:
        if isinstance(ids, int):
            ids = [ids]
        self._tail.extend(ids)
        text = self._tok.decode(self._tail)
        safe = len(text)
        while safe > self._emitted and text[safe - 1] == "�":
            safe -= 1   # incomplete sequence pending more tokens
        delta = text[self._emitted:safe]
        self._emitted = safe
        if len(self._tail) > 2 * self.WINDOW and safe == len(text):
            self._commit(text)
        return delta

    def _commit(self, text: str) -> None:
        """Drop fully-emitted leading ids, keeping WINDOW ids of context.
        Only commits when the tail re-decodes to a clean suffix of the full
        text (BPE boundary tokens can decode differently without their left
        context — then skip and retry at a later boundary)."""
        keep = self._tail[-self.WINDOW:]
        suffix = self._tok.decode(keep)
        if suffix and text.endswith(suffix):
            self._tail = keep
            self._emitted -= len(text) - len(suffix)

    def flush(self) -> str:
        text = self._tok.decode(self._tail)
        delta = text[self._emitted:]
        self._emitted = len(text)
        return delta


def load_tokenizer(path: Optional[str] = None):
    if path and os.path.isdir(path):
        return HFTokenizer(path)
    return ByteTokenizer()
