"""Tokenizers: HF (local dir) + a zero-dependency byte-level fallback.

The environment is zero-egress, so nothing downloads: ``load_tokenizer``
uses a local HF tokenizer dir when given (via ``transformers``), else the
byte fallback (any model with vocab ≥ 259 can serve text demos with it).
"""

from __future__ import annotations

import os
from typing import List, Optional


class ByteTokenizer:
    """UTF-8 bytes + BOS/EOS. ids: 0..255 bytes, 256 BOS, 257 EOS, 258 PAD."""

    bos_id = 256
    eos_id = 257
    pad_id = 258
    vocab_size = 259

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(path: Optional[str] = None):
    if path and os.path.isdir(path):
        return HFTokenizer(path)
    return ByteTokenizer()
