"""North-star SLO suite: PD-disaggregated vs unified serving, measured.

BASELINE.md north star: PD-disagg throughput >= 50% of co-located, p50
TTFT < 200 ms (on TPU v5e-64 for Llama-3-70B). On this machine the suite
runs the same topology as a CPU proxy (tiny model, real processes, real
wire) so the ratio is a *tracked number* across rounds rather than an
aspiration; the identical command reruns on TPU hardware when the chip is
reachable (docs/tpu-runbook.md).

Topologies (all real subprocesses over the wire protocol):

* ``unified`` — one engine server, requests hit it directly.
* ``pd``      — router + prefill + decode (+ shared KV pool wired to the
  prefill), the BASELINE config-3/4 shape; requests hit the router, KV
  bundles cross the wire (Mooncake-style DCN transfer).

Both are offered the SAME Poisson arrival schedule at each rate via
``bench_serving`` (open-loop), after a warmup that exercises every decode
batch bucket so XLA compilation never lands in a measured TTFT.

Usage:
    python -m rbg_tpu.engine.bench_slo --rates 8,16,24 --requests 96 \
        --json-out SLO_r05.json

Emits a markdown table (stdout) and, with --json-out, a BENCH-style JSON
artifact carrying the exact per-run command equivalents and the 1-min
load average observed before each measurement (docs/benchmarks.md
reproducibility rule: no number without its command + load note).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List

from rbg_tpu.engine import bench_serving


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_ready(port: int, timeout: float = 240.0) -> None:
    from rbg_tpu.engine.protocol import request_once
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            h, _, _ = request_once(f"127.0.0.1:{port}", {"op": "health"},
                                   timeout=5)
            if h and h.get("ok"):
                return
        except OSError:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"server on {port} never became ready")


class _Topology:
    """Spawn + tear down one serving topology (scrubbed CPU env unless the
    caller passes a TPU-ready env)."""

    def __init__(self, kind: str, engine_args: List[str], env: dict,
                 max_batch: int, decode_replicas: int = 1):
        self.kind = kind
        self.procs: List[subprocess.Popen] = []
        self.max_batch = max_batch
        self.engine_ports: List[int] = []
        ports: Dict[str, int] = {}
        try:
            if kind == "unified":
                ports["front"] = _free_port()
                self._spawn(["-m", "rbg_tpu.engine.server",
                             "--mode", "unified",
                             "--port", str(ports["front"])] + engine_args, env)
                _wait_ready(ports["front"])
                self.engine_ports = [ports["front"]]
            elif kind == "pd":
                for name in ("pool", "prefill", "front"):
                    ports[name] = _free_port()
                decode_ports = [_free_port() for _ in range(decode_replicas)]
                page = _flag(engine_args, "--page-size", "16")
                self._spawn(["-m", "rbg_tpu.engine.kvpool",
                             "--port", str(ports["pool"]),
                             "--page-size", page], env)
                self._spawn(["-m", "rbg_tpu.engine.server",
                             "--mode", "prefill",
                             "--port", str(ports["prefill"]),
                             "--kv-pool", f"127.0.0.1:{ports['pool']}"]
                            + engine_args, env)
                for dp in decode_ports:
                    self._spawn(["-m", "rbg_tpu.engine.server",
                                 "--mode", "decode",
                                 "--port", str(dp)] + engine_args, env)
                backends = {"prefill": [f"127.0.0.1:{ports['prefill']}"],
                            "decode": [f"127.0.0.1:{dp}"
                                       for dp in decode_ports]}
                self._spawn(["-m", "rbg_tpu.engine.router",
                             "--port", str(ports["front"]),
                             "--backends", json.dumps(backends)], env)
                for port in [ports["prefill"], ports["front"]] + decode_ports:
                    _wait_ready(port)
                self.engine_ports = [ports["prefill"]] + decode_ports
            else:
                raise ValueError(kind)
        except BaseException:
            self.stop()
            raise
        self.addr = f"127.0.0.1:{ports['front']}"

    def _spawn(self, argv: List[str], env: dict) -> None:
        self.procs.append(subprocess.Popen([sys.executable] + argv, env=env))

    def warmup(self, input_len: int) -> None:
        """Compile every jit bucket variant on every engine in the
        topology via the server's ``warmup`` op (a variant first hit
        mid-measurement shows up as a seconds-long stall — observed as a
        9x swing between identical runs), then a short full-batch wave
        through the FRONT door so the router / PD-transfer / pool paths
        are exercised end to end too."""
        import threading

        from rbg_tpu.engine.protocol import request_once
        import numpy as np
        token = os.environ.get("RBG_DATA_TOKEN") or None

        def req(extra):
            # Token-gated deployments (RBG_DATA_TOKEN set) must be
            # benchmarkable — attach the same credential the topology's
            # own processes inherited from this environment.
            return {**extra, "token": token} if token else extra

        for port in self.engine_ports:
            resp, _, _ = request_once(
                f"127.0.0.1:{port}",
                req({"op": "warmup", "input_len": input_len}), timeout=900)
            if not (resp or {}).get("ok"):
                raise RuntimeError(f"warmup failed on :{port}: {resp}")
        rng = np.random.default_rng(987)
        threads = []
        for _ in range(self.max_batch):
            prompt = rng.integers(200, 250, size=input_len).tolist()
            t = threading.Thread(
                target=lambda p=prompt: request_once(
                    self.addr, req({"op": "generate", "prompt": p,
                                    "max_new_tokens": 4}), timeout=600),
                daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=600)

    def stop(self) -> None:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _flag(args: List[str], name: str, default: str) -> str:
    return args[args.index(name) + 1] if name in args else default


def measure(kind: str, rates: List[float], args, env) -> List[dict]:
    engine_args = ["--model", args.model,
                   "--page-size", str(args.page_size),
                   "--num-pages", str(args.num_pages),
                   "--max-seq-len", str(args.max_seq_len),
                   "--max-batch", str(args.max_batch),
                   "--prefill-chunk", str(args.prefill_chunk),
                   "--use-pallas", args.use_pallas]
    topo = _Topology(kind, engine_args, env, args.max_batch,
                     decode_replicas=args.pd_decode_replicas)
    rows = []
    try:
        topo.warmup(args.input_len)
        for rate in rates:
            bargs = argparse.Namespace(
                requests=args.requests, rate=rate,
                input_len=args.input_len, output_len=args.output_len,
                model=args.model, page_size=args.page_size,
                num_pages=args.num_pages, max_seq_len=args.max_seq_len,
                max_batch=args.max_batch, use_pallas=args.use_pallas,
                multi_step=1, speculative="off", addr=topo.addr,
                token=os.environ.get("RBG_DATA_TOKEN", ""),
                slo_ttft_s=args.slo_ttft_s, slo_tpot_s=args.slo_tpot_s,
                seed=args.seed, json=True)
            load1 = os.getloadavg()[0]
            out = bench_serving.run(bargs)
            out["setup"] = kind
            out["load1_before"] = round(load1, 2)
            replicas = (f" [pd topology: --pd-decode-replicas "
                        f"{args.pd_decode_replicas}]"
                        if kind == "pd" else "")
            out["command"] = (
                f"python -m rbg_tpu.engine.bench_serving --addr <{kind}> "
                f"--requests {args.requests} --rate {rate} "
                f"--input-len {args.input_len} --output-len {args.output_len} "
                f"--model {args.model} --max-batch {args.max_batch}"
                f"{replicas}")
            rows.append(out)
    finally:
        topo.stop()
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("rbg-tpu SLO suite (PD-disagg vs unified)")
    ap.add_argument("--rates", default="8,16,24",
                    help="comma-separated offered rates (req/s)")
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--input-len", type=int, default=32)
    ap.add_argument("--output-len", type=int, default=32)
    ap.add_argument("--model", default="tiny")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=512)
    ap.add_argument("--max-seq-len", type=int, default=512)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--use-pallas", default="never")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slo-ttft-s", type=float, default=0.2,
                    help="TTFT target for windowed goodput (default: the "
                         "BASELINE north-star 200 ms; 0 disables)")
    ap.add_argument("--slo-tpot-s", type=float, default=0.1,
                    help="per-output-token target for goodput (0 disables)")
    ap.add_argument("--json-out", default="",
                    help="write the BENCH-style artifact here")
    ap.add_argument("--setups", default="unified,pd")
    def _positive(v):
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError("must be >= 1")
        return n

    ap.add_argument("--pd-decode-replicas", type=_positive, default=1,
                    help="decode replicas in the pd topology (the router "
                         "least-loads across them) — the knob the "
                         "saturation ratio scales with")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                    help="cpu = scrubbed CPU-proxy subprocesses (default); "
                         "tpu = inherit the TPU environment (one engine "
                         "process at a time touches the chip — unified and "
                         "pd runs are sequential, but a pd TOPOLOGY is "
                         "multi-process: only run it on real multi-chip "
                         "hosts, per docs/tpu-runbook.md)")
    args = ap.parse_args(argv)
    rates = [float(r) for r in args.rates.split(",") if r]

    # The executor's env contract (RBG_SERVE_PORT & co) must not leak into
    # spawned topologies — it would override every --port with ONE value.
    drop = {"RBG_SERVE_PORT": None, "RBG_PORT_SERVE": None,
            "RBG_KV_POOL_ADDR": None}
    if args.platform == "cpu":
        from rbg_tpu.utils import scrubbed_cpu_env
        env = scrubbed_cpu_env(extra=drop)
    else:
        env = {k: v for k, v in os.environ.items() if k not in drop}

    results: Dict[str, List[dict]] = {}
    for kind in args.setups.split(","):
        results[kind] = measure(kind, rates, args, env)

    # The north-star ratio at each matched rate.
    ratios = []
    if "unified" in results and "pd" in results:
        for u, p in zip(results["unified"], results["pd"]):
            ratios.append({
                "rate_rps": u["offered_rate_rps"],
                "pd_over_unified_throughput": round(
                    p["output_tok_per_s"] / u["output_tok_per_s"], 3)
                    if u["output_tok_per_s"] else None,
                "pd_ttft_p50_s": p["ttft_s"]["p50"],
                "unified_ttft_p50_s": u["ttft_s"]["p50"],
                # Attainment, not just latency quantiles: req/s that met
                # BOTH SLO targets — the trajectory SLO_r*.json tracks.
                "pd_goodput_rps": p.get("goodput_rps"),
                "unified_goodput_rps": u.get("goodput_rps"),
            })

    hdr = (f"| setup | rate rps | done | tok/s | ttft p50/p99 s | "
           f"itl p50/p99 ms | e2e p50/p99 s | load1 |")
    print(hdr)
    print("|" + "---|" * 8)
    for kind, rows in results.items():
        for r in rows:
            print(f"| {kind} | {r['offered_rate_rps']} "
                  f"| {r['completed']}/{r['requests']} "
                  f"| {r['output_tok_per_s']} "
                  f"| {r['ttft_s']['p50']}/{r['ttft_s']['p99']} "
                  f"| {r['itl_ms']['p50']}/{r['itl_ms']['p99']} "
                  f"| {r['e2e_s']['p50']}/{r['e2e_s']['p99']} "
                  f"| {r['load1_before']} |")
    for rt in ratios:
        print(f"ratio @ {rt['rate_rps']} rps: PD/unified throughput = "
              f"{rt['pd_over_unified_throughput']}  "
              f"(PD ttft p50 {rt['pd_ttft_p50_s']}s, PD goodput "
              f"{rt['pd_goodput_rps']} rps vs unified "
              f"{rt['unified_goodput_rps']} rps)")

    if args.json_out:
        artifact = {
            "suite": "pd_vs_unified_slo",
            "model": args.model,
            "hardware": "cpu-proxy" if args.platform == "cpu" else "tpu",
            "input_len": args.input_len, "output_len": args.output_len,
            "pd_decode_replicas": args.pd_decode_replicas,
            "slo_targets": {"ttft_s": args.slo_ttft_s,
                            "tpot_s": args.slo_tpot_s},
            "results": results, "north_star_ratios": ratios,
        }
        with open(args.json_out, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"wrote {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
