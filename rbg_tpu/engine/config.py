"""Engine configuration."""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from rbg_tpu.models.config import ModelConfig, get_config


@dataclasses.dataclass
class EngineConfig:
    model: str = "tiny"
    page_size: int = 16
    num_pages: int = 256                    # KV pool size (pages)
    max_batch: int = 8                      # decode batch ceiling
    max_seq_len: int = 512                  # per-sequence ceiling
    prefill_chunk: int = 64                 # chunked-prefill bucket
    decode_buckets: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    enable_radix_cache: bool = True
    # Host-DRAM KV spill tier (engine/kvtier.py): when > 0, radix-cache
    # evictions spill their pages into a host trie bounded to this many
    # bytes instead of discarding them, and admission promotes host-held
    # prefixes back onto device. Mooncake's "more storage for less
    # computation" level — needs the radix cache; int8 KV pools keep it
    # off (spilled pages would need their scales carried too).
    host_tier_bytes: int = 0
    # Decode steps fused into ONE device dispatch (lax.scan window) — the
    # JetStream-style device-side decode loop. Each window samples K tokens
    # per sequence before control returns to the host, amortizing dispatch
    # overhead K-fold; tokens stream out in bursts of K (ITL burstiness is
    # the price, throughput the prize). Stop-token checks still happen
    # host-side, so up to K-1 speculative KV writes are discarded on stop.
    multi_step: int = 1
    # Speculative decoding: "ngram" = prompt-lookup drafting (no draft
    # model) + one (B, spec_k+1) verify forward per step. Because sampling
    # randomness is position-keyed (sampler.py), output is bit-identical
    # to non-speculative decoding — greedy AND sampled. Best on
    # repetitive/structured text; host-syncs every step, so it replaces
    # (and excludes) the fused multi_step window.
    speculative: str = "off"                # off | ngram
    spec_k: int = 4                         # max drafted tokens per step
    spec_ngram: int = 3                     # trailing n-gram for lookup
    # Device-resident grammar decode: finite-state grammars (regex /
    # json_schema) compile to dense token-level transition tables
    # (next_state[S, V] int32 + legal[S, V] bool) uploaded once per
    # (grammar, vocab), so constrained rows run INSIDE the fused
    # multi-step scan with zero per-token host syncs. "auto" tables every
    # eligible grammar and falls back to the host-synced mask path when
    # the reachable state count exceeds grammar_state_budget (or for the
    # pushdown JSON grammar, which has no finite table); "off" keeps
    # every constrained row on the host-synced path.
    grammar_table: str = "auto"             # auto | off
    # Max token-level states materialized per grammar. A grammar's table
    # costs pow2(S) × V × 5 bytes (int32 + bool) host- AND device-side
    # (device blocks are pow-2-padded and live while the grammar sits in
    # the 64-entry pattern/schema LRU — budget the AGGREGATE against
    # your vocab and HBM, worst case 64 × budget × V × 5).
    grammar_state_budget: int = 512
    use_pallas: str = "auto"                # auto | always | never
    # Ragged unified prefill/decode dispatch (continuous batching): while
    # any row is mid-prefill, the WHOLE batch — prefill chunks and decode
    # steps together — rides one ragged forward (ops/ragged_paged_attention)
    # instead of phase-split prefill-then-decode programs, and the fused
    # decode scan shortens its window to absorb waiting joins. "off" keeps
    # the split paths (the bit-identity baseline). Pure-decode batches use
    # the fused multi-step scan either way; MLA models, speculative mode,
    # and LoRA-mixed batches fall back to the split paths automatically.
    ragged: str = "auto"                    # auto | off
    # Per-request SLO targets the serving loop judges every FINISHED
    # request against (obs/slo.py): seconds to first token, and seconds
    # per output token after the first. Judgment is cheap host-side
    # bookkeeping at finish — it publishes rbg_slo_* attainment/goodput
    # series but never gates admission. 0 disables that dimension (it
    # always counts as met).
    slo_ttft_s: float = 2.0
    slo_tpot_s: float = 0.5
    # Predictive early rejection (Mooncake's overload story): admission
    # predicts TTFT — measured queue wait plus prefill time net of the
    # prefix hit this request would get — and sheds at INGRESS with
    # retry_after_s when the prediction exceeds early_reject_factor ×
    # slo_ttft_s, before any prefill compute is spent. "auto" arms it
    # whenever slo_ttft_s > 0; "off" keeps the PR-2 deadline-only gate.
    early_reject: str = "off"               # off | auto
    early_reject_factor: float = 1.5
    mode: str = "unified"                   # unified | prefill | decode
    mesh_spec: Optional[dict] = None        # {"dp": 1, "tp": 4} — from discovery
    checkpoint_path: str = ""               # orbax dir or local HF dir
    kv_dtype: str = "model"                 # model | int8 (quantized KV pool)
    vocab_size: int = 0                     # override preset vocab (0 = keep)
    seed: int = 0

    @property
    def model_config(self) -> ModelConfig:
        if self.vocab_size:
            return get_config(self.model, vocab_size=self.vocab_size)
        return get_config(self.model)

    @property
    def max_pages_per_seq(self) -> int:
        return (self.max_seq_len + self.page_size - 1) // self.page_size

    def validate(self) -> None:
        if self.max_batch > max(self.decode_buckets):
            raise ValueError("max_batch exceeds largest decode bucket")
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if self.multi_step < 1:
            raise ValueError("multi_step must be >= 1")
        if self.speculative not in ("off", "ngram"):
            raise ValueError(f"speculative {self.speculative!r} not in "
                             "(off, ngram)")
        if self.speculative != "off":
            if self.multi_step != 1:
                raise ValueError("speculative decoding and multi_step are "
                                 "mutually exclusive (both own the decode "
                                 "dispatch)")
            if self.spec_k < 1 or self.spec_ngram < 1:
                raise ValueError("spec_k and spec_ngram must be >= 1")
        if self.ragged not in ("auto", "off"):
            raise ValueError(f"ragged {self.ragged!r} not in (auto, off)")
        if self.grammar_table not in ("auto", "off"):
            raise ValueError(f"grammar_table {self.grammar_table!r} not in "
                             "(auto, off)")
        if self.grammar_state_budget < 2:
            raise ValueError("grammar_state_budget must be >= 2 (initial "
                             "state + at least one successor)")
        if self.slo_ttft_s < 0 or self.slo_tpot_s < 0:
            raise ValueError("slo_ttft_s / slo_tpot_s must be >= 0 "
                             "(0 disables that SLO dimension)")
        if self.host_tier_bytes < 0:
            raise ValueError("host_tier_bytes must be >= 0 (0 disables "
                             "the host spill tier)")
        if self.host_tier_bytes and self.kv_dtype == "int8":
            raise ValueError("host_tier_bytes with kv_dtype='int8': the "
                             "spill tier does not carry page scales yet")
        if self.host_tier_bytes and not self.enable_radix_cache:
            raise ValueError(
                "host_tier_bytes needs the radix cache (spills come from "
                "its evictions) — a silently absent tier would discard "
                "every evicted prefix the operator budgeted RAM to keep")
        if self.early_reject not in ("off", "auto"):
            raise ValueError(f"early_reject {self.early_reject!r} not in "
                             "(off, auto)")
        if self.early_reject_factor <= 0:
            raise ValueError("early_reject_factor must be > 0")
        if self.kv_dtype not in ("model", "int8"):
            raise ValueError(f"kv_dtype {self.kv_dtype!r} not in (model, int8)")
        if self.kv_dtype == "int8" and self.mode != "unified":
            raise ValueError(
                "int8 KV is unified-mode only for now (PD bundles carry "
                "unquantized pages)")
        self.model_config  # fail fast on an unknown preset


@dataclasses.dataclass
class SamplingParams:
    max_new_tokens: int = 16
    temperature: float = 0.0        # 0 = greedy
    top_k: int = 0                  # 0 = full vocab
    top_p: float = 1.0              # nucleus mass; 1.0 = disabled
    min_p: float = 0.0              # min prob ratio vs argmax; 0.0 = disabled
    repetition_penalty: float = 1.0  # >1 discourages prompt+output tokens
    presence_penalty: float = 0.0   # subtract once per distinct output token
    frequency_penalty: float = 0.0  # subtract per output occurrence
    seed: Optional[int] = None      # per-request PRNG stream (reproducible)
    logprobs: bool = False          # emit chosen-token logprob per step
    json_mode: bool = False         # grammar-constrained: output is valid JSON
    regex: Optional[str] = None     # grammar-constrained: output matches
                                    # this anchored byte-level regex
    json_schema: Optional[dict] = None  # grammar-constrained: output is
                                        # compact JSON valid under this
                                        # schema subset (guided_json)
    lora: Optional[str] = None      # adapter name (engine-registered)
    stop_token: Optional[int] = None

    def needs_penalties(self) -> bool:
        return (self.repetition_penalty != 1.0
                or self.presence_penalty != 0.0
                or self.frequency_penalty != 0.0)

    def validate(self) -> None:
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if not 0.0 <= self.min_p < 1.0:
            raise ValueError("min_p must be in [0, 1)")
        if self.repetition_penalty <= 0:
            raise ValueError("repetition_penalty must be > 0")
        constraints = ((1 if self.json_mode else 0)
                       + (1 if self.regex is not None else 0)
                       + (1 if self.json_schema is not None else 0))
        if constraints > 1:
            raise ValueError("json_mode, regex, and json_schema are "
                             "mutually exclusive constraints")

    @classmethod
    def from_wire(cls, obj: dict, *, default_max_tokens: int = 16,
                  stop_token: Optional[int] = None) -> "SamplingParams":
        """Parse sampling fields off a protocol message (engine server /
        decode_bundle / HTTP front end all speak the same field names)."""
        sp = cls(
            max_new_tokens=int(obj.get("max_new_tokens", default_max_tokens)),
            temperature=float(obj.get("temperature", 0.0)),
            top_k=int(obj.get("top_k", 0)),
            top_p=float(obj.get("top_p", 1.0)),
            min_p=float(obj.get("min_p", 0.0)),
            repetition_penalty=float(obj.get("repetition_penalty", 1.0)),
            presence_penalty=float(obj.get("presence_penalty", 0.0)),
            frequency_penalty=float(obj.get("frequency_penalty", 0.0)),
            seed=(int(obj["seed"]) if obj.get("seed") is not None else None),
            logprobs=bool(obj.get("logprobs", False)),
            json_mode=bool(obj.get("json_mode", False)),
            # `is not None` checks: regex="" means "empty output only" and
            # json_schema={} means "any JSON" — truthiness would silently
            # drop both and return UNCONSTRAINED output.
            regex=(str(obj["regex"]) if obj.get("regex") is not None
                   else None),
            json_schema=(dict(obj["json_schema"])
                         if obj.get("json_schema") is not None else None),
            lora=(str(obj["lora"]) if obj.get("lora") else None),
            stop_token=(obj.get("stop_token") if obj.get("stop_token") is None
                        else int(obj["stop_token"])),
        )
        if stop_token is not None and sp.stop_token is None:
            sp.stop_token = stop_token
        sp.validate()
        return sp


def warm_prompt(input_len: int, wave: int = 0, row: int = 0) -> list:
    """Deterministic warmup prompt, distinct per (wave, row) — identical
    prompts would radix-hit and skip the very prefill shapes warmup exists
    to compile. Token ids stay in [1, 200): inside every preset's vocab
    and clear of special ids. The ONE generator for all warmup paths
    (EngineService / DecodeService / PrefillWorker)."""
    base = (wave * 131 + row * 17) % 199
    return [1 + (base + j) % 199 for j in range(input_len)]
