"""Engine configuration."""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from rbg_tpu.models.config import ModelConfig, get_config


@dataclasses.dataclass
class EngineConfig:
    model: str = "tiny"
    page_size: int = 16
    num_pages: int = 256                    # KV pool size (pages)
    max_batch: int = 8                      # decode batch ceiling
    max_seq_len: int = 512                  # per-sequence ceiling
    prefill_chunk: int = 64                 # chunked-prefill bucket
    decode_buckets: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    enable_radix_cache: bool = True
    # Decode steps fused into ONE device dispatch (lax.scan window) — the
    # JetStream-style device-side decode loop. Each window samples K tokens
    # per sequence before control returns to the host, amortizing dispatch
    # overhead K-fold; tokens stream out in bursts of K (ITL burstiness is
    # the price, throughput the prize). Stop-token checks still happen
    # host-side, so up to K-1 speculative KV writes are discarded on stop.
    multi_step: int = 1
    use_pallas: str = "auto"                # auto | always | never
    mode: str = "unified"                   # unified | prefill | decode
    mesh_spec: Optional[dict] = None        # {"dp": 1, "tp": 4} — from discovery
    checkpoint_path: str = ""               # orbax dir or local HF dir
    kv_dtype: str = "model"                 # model | int8 (quantized KV pool)
    seed: int = 0

    @property
    def model_config(self) -> ModelConfig:
        return get_config(self.model)

    @property
    def max_pages_per_seq(self) -> int:
        return (self.max_seq_len + self.page_size - 1) // self.page_size

    def validate(self) -> None:
        if self.max_batch > max(self.decode_buckets):
            raise ValueError("max_batch exceeds largest decode bucket")
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if self.multi_step < 1:
            raise ValueError("multi_step must be >= 1")
        if self.kv_dtype not in ("model", "int8"):
            raise ValueError(f"kv_dtype {self.kv_dtype!r} not in (model, int8)")
        if self.kv_dtype == "int8" and self.mode != "unified":
            raise ValueError(
                "int8 KV is unified-mode only for now (PD bundles carry "
                "unquantized pages)")
        if self.kv_dtype == "int8" and self.use_pallas == "always":
            raise ValueError(
                "use_pallas='always' is incompatible with kv_dtype='int8' — "
                "the Pallas kernel does not dequantize yet; use 'auto'")


@dataclasses.dataclass
class SamplingParams:
    max_new_tokens: int = 16
    temperature: float = 0.0        # 0 = greedy
    top_k: int = 0                  # 0 = full vocab
    stop_token: Optional[int] = None
