"""Prefill/decode disaggregation: KV-cache handoff between engines.

Reference context: RBG's flagship topology is PD-disagg serving (router →
prefill → decode roles, ``examples/inference/pd-disagg-*.yaml``) with
Mooncake-style KV transfer (``keps/74-mooncake-integration``; Mooncake paper
in PAPERS.md). The control plane places the roles; THIS module is the data
path between them:

* ``PrefillWorker`` — runs prompts to first-token on a prefill engine and
  exports the sequence's KV pages as a ``KVBundle``.
* ``DecodeWorker`` — imports a bundle into its own page pool and continues
  decoding with continuous batching.
* ``PDPair`` — in-process pair (same chip / same slice: the transfer is a
  device gather+scatter). Cross-process transfer sends the same bundle over
  the transport in ``rbg_tpu.engine.server`` (DCN analog); on multi-slice
  TPU the placement layer keeps the pair within one ICI domain so the
  transfer rides ICI (BASELINE.json north star).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.engine import Engine, Request
from rbg_tpu.engine.kvcache import pages_for_tokens
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs import trace


@dataclasses.dataclass
class KVBundle:
    """A sequence's transferable KV state."""

    prompt: List[int]
    first_token: int
    k_data: np.ndarray   # [L, n_pages, page, KV, hd]
    v_data: np.ndarray

    @property
    def nbytes(self) -> int:
        return self.k_data.nbytes + self.v_data.nbytes


class PrefillWorker:
    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None, pool=None):
        """``pool``: optional ``rbg_tpu.engine.kvpool.KVPoolClient`` — the
        SHARED cross-request/cross-replica prefix store (Mooncake-store
        analog, keps/74). Consulted before computing, published to after.
        Pool failures degrade to cold prefill, never to request failure."""
        cfg = dataclasses.replace(cfg, mode="prefill")
        self.engine = Engine(cfg, params=params, mesh=mesh)
        self.pool = pool
        if pool is not None and getattr(pool, "page_size", None) is None:
            pool.page_size = cfg.page_size  # handshake: server verifies
        self.metrics = {"bundles": 0, "bytes_out": 0, "transfer_s": 0.0,
                        "pool_hits": 0, "pool_hit_tokens": 0,
                        "pool_exports": 0, "pool_errors": 0}

    def warmup(self, input_len: int = 32) -> float:
        """Compile the prefill + bundle-export path (jit variants keyed on
        chunk/bucket shapes and the export gather on the page count)
        before traffic — same rationale as ``_BatchService.warmup``. The
        shared pool is bypassed: warmup KV must not pollute the
        cross-replica prefix store."""
        from rbg_tpu.engine.config import warm_prompt

        t0 = time.perf_counter()
        pool, self.pool = self.pool, None
        try:
            self.prefill(warm_prompt(input_len))
        finally:
            self.pool = pool
        return time.perf_counter() - t0

    def prefill(self, prompt: List[int],
                sampling: Optional[SamplingParams] = None,
                deadline: Optional[float] = None) -> KVBundle:
        """Run one prompt to its first token; export KV pages.

        ``deadline`` (absolute ``time.monotonic()``) aborts a long chunked
        prefill between chunks once the client's budget is spent — the
        pages recycle immediately instead of finishing a bundle nobody is
        waiting for. Raises the service-layer ``DeadlineExceeded`` so the
        server maps it to the structured wire code."""
        sampling = sampling or SamplingParams()
        one = dataclasses.replace(sampling, max_new_tokens=1)
        ps = self.engine.cfg.page_size
        rid = None
        matched = 0
        # Adapter requests skip the shared pool: pooled KV is base-model KV.
        if self.pool is not None and sampling.lora is None:
            # Keep at least the prompt's last token for prefill (logits) —
            # same contract as the in-process radix cache.
            try:
                matched, kd, vd = self.pool.match(prompt[:-1])
            except (OSError, RuntimeError):
                self.metrics["pool_errors"] += 1
                matched = 0
            if matched:
                try:
                    rid = self.engine.add_request_with_prefix(
                        prompt, one, matched, kd, vd)
                except ValueError:
                    # Malformed pool data (e.g. misaligned prefix) must
                    # degrade to a cold prefill, never fail the request.
                    self.metrics["pool_errors"] += 1
                    rid = None
                if rid is None:
                    matched = 0  # no free pages / bad data: cold prefill
                else:
                    self.metrics["pool_hits"] += 1
                    self.metrics["pool_hit_tokens"] += matched
        if rid is None:
            rid = self.engine.add_request(prompt, one)
        first = None
        while first is None:
            if deadline is not None and time.monotonic() >= deadline:
                from rbg_tpu.engine.protocol import DeadlineExceeded
                self.engine.cancel_request(rid)
                self.metrics["deadline_aborts"] = (
                    self.metrics.get("deadline_aborts", 0) + 1)
                raise DeadlineExceeded(
                    "deadline spent mid-prefill (aborted, pages recycled)")
            for ev in self.engine.step():
                if ev.request_id == rid:
                    first = ev.token
        req = self.engine.requests[rid]
        n_pages = pages_for_tokens(len(prompt), self.engine.cfg.page_size)
        page_ids = jnp.asarray(req.pages[:n_pages], jnp.int32)
        t0 = time.perf_counter()
        k = np.asarray(self.engine.cache.k_pages[:, page_ids])
        v = np.asarray(self.engine.cache.v_pages[:, page_ids])
        self.metrics["transfer_s"] += time.perf_counter() - t0
        self.engine.release_request(rid)
        if self.pool is not None and sampling.lora is None:
            # Publish the page-aligned prompt prefix for future requests
            # (idempotent: the store refreshes rather than duplicates).
            # Adapter KV never enters the pool — it is not base-model KV.
            full = len(prompt) // ps
            if full > matched // ps:
                try:
                    self.pool.put(prompt, k[:, :full], v[:, :full])
                    self.metrics["pool_exports"] += 1
                except (OSError, RuntimeError):
                    self.metrics["pool_errors"] += 1
        bundle = KVBundle(prompt=list(prompt), first_token=first, k_data=k, v_data=v)
        self.metrics["bundles"] += 1
        self.metrics["bytes_out"] += bundle.nbytes
        return bundle


class DecodeWorker:
    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None, mesh=None):
        cfg = dataclasses.replace(cfg, mode="decode", enable_radix_cache=False)
        self.engine = Engine(cfg, params=params, mesh=mesh)
        self.metrics = {"bundles": 0, "bytes_in": 0}

    def inject(self, bundle: KVBundle,
               sampling: Optional[SamplingParams] = None) -> int:
        """Import a KV bundle and start decoding it. Returns the request id.
        The first token is accounted as output[0] (already produced).

        The page-pool import (the on-device half of the prefill→decode KV
        handoff) gets its own ``pd.kv_handoff`` span under the ambient
        request span — the ROADMAP transfer-plane work (chunked /
        layer-overlapped streaming) lands inside this same hop and
        inherits the instrumentation."""
        sampling = sampling or SamplingParams()
        eng = self.engine
        prompt = bundle.prompt
        eng._check_prompt(prompt)
        # Before alloc — a raise must not leak pages.
        eng._grammar_check(sampling)
        lora_idx = eng._resolve_lora(sampling)
        n_pages = bundle.k_data.shape[1]
        need = pages_for_tokens(len(prompt) + 1, eng.cfg.page_size)
        pages = eng._alloc(need)
        if pages is None:
            raise RuntimeError("decode engine out of KV pages")
        # Context-manager form: a raise in the page import must still end
        # the span or the trace finalizes incomplete.
        with trace.child(obs_names.SPAN_PD_KV_HANDOFF,
                         bytes=bundle.nbytes, pages=int(n_pages)):
            ids = jnp.asarray(pages[:n_pages], jnp.int32)
            from rbg_tpu.engine.kvcache import PagedKVCache
            eng.cache = PagedKVCache(
                k_pages=eng.cache.k_pages.at[:, ids].set(
                    jnp.asarray(bundle.k_data, eng.cache.k_pages.dtype)),
                v_pages=eng.cache.v_pages.at[:, ids].set(
                    jnp.asarray(bundle.v_data, eng.cache.v_pages.dtype)),
            )
        req = Request(prompt, sampling)
        req.lora_idx = lora_idx
        g = eng._grammar_for(sampling)
        if g is not None:
            # The first token was sampled prefill-side under the grammar
            # mask — fold it in so decode continues from the right state.
            # This must cover ALL THREE constraint kinds: a json_mode
            # request without req.grammar used to crash the decode batch
            # (advance_token on a None grammar), and regex/json_schema
            # requests silently decoded UNCONSTRAINED.
            nxt = g.advance_token(g.initial(), bundle.first_token)
            if nxt is None:
                # A grammar-wired prefill can't produce this; it means the
                # prefill peer ignored the constraint (mixed-version
                # deploy). Reject rather than emit corrupt "constrained"
                # output.
                eng.allocator.release(pages)
                raise ValueError(
                    f"first token {bundle.first_token} violates the "
                    "request's grammar constraint — prefill peer ignored "
                    "json_mode/regex/json_schema?")
            req.grammar = g
            req.gstate = nxt
        req.state = "running"
        req.pages = pages
        req.seq_len = len(prompt)
        req.prefill_pos = len(prompt)
        req.output = [bundle.first_token]
        req.last_token = bundle.first_token
        req.t_first = time.perf_counter()
        eng.requests[req.id] = req
        eng.running.append(req)
        self.metrics["bundles"] += 1
        self.metrics["bytes_in"] += bundle.nbytes
        # Already complete (max_new_tokens == 1 or stop token hit): finish
        # now so its pages recycle.
        if (len(req.output) >= sampling.max_new_tokens
                or (sampling.stop_token is not None
                    and bundle.first_token == sampling.stop_token)):
            eng._finish(req)
        return req.id


class PDPair:
    """In-process prefill+decode pair — the single-host PD-disagg unit the
    bench exercises (BASELINE configs 3-4)."""

    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None):
        self.prefill = PrefillWorker(cfg, params=params, mesh=mesh)
        # Decode shares weights with prefill (same chip in-process).
        self.decode = DecodeWorker(cfg, params=self.prefill.engine.params, mesh=mesh)

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None,
                 collect_ttft: bool = False):
        sampling = sampling or SamplingParams()
        outputs: Dict[int, List[int]] = {}
        ttft: List[float] = []
        order = []
        for p in prompts:
            t0 = time.perf_counter()
            bundle = self.prefill.prefill(p, sampling)
            rid = self.decode.inject(bundle, sampling)
            ttft.append(time.perf_counter() - t0)
            outputs[rid] = [bundle.first_token]
            order.append(rid)
        while self.decode.engine.has_work():
            for ev in self.decode.engine.step():
                if ev.request_id in outputs:
                    outputs[ev.request_id].append(ev.token)
        result = [outputs[r] for r in order]
        return (result, ttft) if collect_ttft else result
