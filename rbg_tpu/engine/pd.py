"""Prefill/decode disaggregation: KV-cache handoff between engines.

Reference context: RBG's flagship topology is PD-disagg serving (router →
prefill → decode roles, ``examples/inference/pd-disagg-*.yaml``) with
Mooncake-style KV transfer (``keps/74-mooncake-integration``; Mooncake paper
in PAPERS.md). The control plane places the roles; THIS module is the data
path between them:

* ``PrefillWorker`` — runs prompts to first-token on a prefill engine and
  exports the sequence's KV pages: as one ``KVBundle`` (legacy, single
  blob) or as a CHUNKED STREAM over a ``rbg_tpu.kvtransfer`` transport —
  page-aligned, layer-ordered chunks published AS prefill chunks complete,
  so the transfer overlaps the remaining prefill compute.
* ``DecodeWorker`` — imports KV into its own page pool and continues
  decoding with continuous batching. The streaming form writes chunks
  into the page table as they arrive (host staging on transport threads;
  device commits on the engine loop thread, the single-writer contract)
  and admits the row the moment layer coverage is complete for the
  prompt — decode starts before the stream closes.
* ``PDPair`` / ``PDStreamPair`` — in-process pairs (same chip / same
  slice). Cross-process transfer rides ``rbg_tpu.engine.server`` ops
  (``kv_stream`` / ``decode_stream``); on multi-slice TPU the placement
  layer keeps the pair within one ICI domain (BASELINE.json north star).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.engine import Engine, Request
from rbg_tpu.engine.kvcache import PagedKVCache, pages_for_tokens
from rbg_tpu.kvtransfer.chunks import (KVChunk, StreamError, StreamFin,
                                       StreamFirstToken, StreamMeta,
                                       slab_to_chunks)
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs import trace
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_lock

_stream_ids = itertools.count()


def new_stream_id(prefix: str = "kvs") -> str:
    return f"{prefix}-{os.getpid()}-{next(_stream_ids)}"


@dataclasses.dataclass
class KVBundle:
    """A sequence's transferable KV state (the whole-blob form)."""

    prompt: List[int]
    first_token: int
    k_data: np.ndarray   # [L, n_pages, page, KV, hd]
    v_data: np.ndarray

    @property
    def nbytes(self) -> int:
        return self.k_data.nbytes + self.v_data.nbytes


class PushResult:
    """Handle on an in-flight chunked KV push. ``prefill_stream`` returns
    the moment prefill COMPUTE ends (first token exists); the sender
    thread keeps draining queued chunks over the link. ``wait`` joins the
    push; ``error`` is the structured failure, if any."""

    def __init__(self, stream_id: str, meta: StreamMeta):
        self.stream_id = stream_id
        self.meta = meta
        self.first_token: Optional[int] = None
        self.nbytes = 0
        self.push_s = 0.0
        self.chunks = 0
        self._err: Optional[str] = None
        self._done = threading.Event()

    def wait(self, timeout: float = 60.0) -> bool:
        return self._done.wait(timeout)

    def error(self) -> Optional[str]:
        return self._err


class PrefillWorker:
    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None, pool=None, directory=None,
                 advertise_addr: str = "", slice_id: Optional[str] = None):
        """``pool``: optional ``rbg_tpu.engine.kvpool.KVPoolClient`` — the
        SHARED cross-request/cross-replica prefix store (Mooncake-store
        analog, keps/74). Consulted before computing, published to after.
        Pool failures degrade to cold prefill, never to request failure.

        ``directory``: optional cluster prefix directory handle
        (``kvtransfer.PrefixDirectory`` or ``DirectoryClient``). Computed
        page-aligned prefixes are registered under ``advertise_addr`` (this
        replica's serving address) so the router can send prefix-sharing
        requests to ANY holder. ``slice_id`` tags entries for slice-level
        invalidation on preemption (default: $RBG_SLICE_ID)."""
        cfg = dataclasses.replace(cfg, mode="prefill")
        self.engine = Engine(cfg, params=params, mesh=mesh)
        self.pool = pool
        self.directory = directory
        self.advertise_addr = advertise_addr
        self.slice_id = (slice_id if slice_id is not None
                         else os.environ.get("RBG_SLICE_ID", ""))
        if pool is not None and getattr(pool, "page_size", None) is None:
            pool.page_size = cfg.page_size  # handshake: server verifies
        self.metrics = {"bundles": 0, "bytes_out": 0, "transfer_s": 0.0,
                        "pool_hits": 0, "pool_hit_tokens": 0,
                        "pool_exports": 0, "pool_errors": 0,
                        "streams": 0, "stream_chunks": 0,
                        "dir_registers": 0}

    def warmup(self, input_len: int = 32) -> float:
        """Compile the prefill + bundle-export path (jit variants keyed on
        chunk/bucket shapes and the export gather on the page count)
        before traffic — same rationale as ``_BatchService.warmup``. The
        shared pool is bypassed: warmup KV must not pollute the
        cross-replica prefix store."""
        from rbg_tpu.engine.config import warm_prompt

        t0 = time.perf_counter()
        pool, self.pool = self.pool, None
        directory, self.directory = self.directory, None
        try:
            self.prefill(warm_prompt(input_len))
        finally:
            self.pool = pool
            self.directory = directory
        return time.perf_counter() - t0

    # ---- shared prefill core ----

    def _start_request(self, prompt: List[int], one: SamplingParams):
        """Pool-consulted admission. Returns (rid, matched_tokens)."""
        rid = None
        matched = 0
        # Adapter requests skip the shared pool: pooled KV is base-model KV.
        if self.pool is not None and one.lora is None:
            # Keep at least the prompt's last token for prefill (logits) —
            # same contract as the in-process radix cache.
            try:
                matched, kd, vd = self.pool.match(prompt[:-1])
            except (OSError, RuntimeError):
                self.metrics["pool_errors"] += 1
                matched = 0
            if matched:
                try:
                    rid = self.engine.add_request_with_prefix(
                        prompt, one, matched, kd, vd)
                except ValueError:
                    # Malformed pool data (e.g. misaligned prefix) must
                    # degrade to a cold prefill, never fail the request.
                    self.metrics["pool_errors"] += 1
                    rid = None
                if rid is None:
                    matched = 0  # no free pages / bad data: cold prefill
                else:
                    self.metrics["pool_hits"] += 1
                    self.metrics["pool_hit_tokens"] += matched
        if rid is None:
            rid = self.engine.add_request(prompt, one)
        return rid, matched

    def _run_to_first(self, rid: int, deadline: Optional[float],
                      on_step: Optional[Callable[[Request], None]] = None
                      ) -> int:
        """Step the engine until ``rid`` emits its first token. ``on_step``
        fires after every step with the request — the chunk-publish hook."""
        first = None
        req = self.engine.requests[rid]
        while first is None:
            if deadline is not None and time.monotonic() >= deadline:
                from rbg_tpu.engine.protocol import DeadlineExceeded
                self.engine.cancel_request(rid)
                self.metrics["deadline_aborts"] = (
                    self.metrics.get("deadline_aborts", 0) + 1)
                raise DeadlineExceeded(
                    "deadline spent mid-prefill (aborted, pages recycled)")
            for ev in self.engine.step():
                if ev.request_id == rid:
                    first = ev.token
            if on_step is not None:
                on_step(req)
        return first

    def _export_pages(self, req: Request, lo: int, hi: int):
        """Host copy of device pages [lo, hi) of this request — the
        transfer payload. Device→host sync; callers keep it off any
        critical section."""
        ids = jnp.asarray(req.pages[lo:hi], jnp.int32)
        t0 = time.perf_counter()
        # One batched fetch: the two page slabs resolve in a single
        # transfer instead of two sequential round-trip syncs.
        # lint: allow[jit-hygiene] the transfer payload itself — exporting KV to the decode worker IS a host copy
        k, v = jax.device_get((self.engine.cache.k_pages[:, ids],
                               self.engine.cache.v_pages[:, ids]))
        self.metrics["transfer_s"] += time.perf_counter() - t0
        return k, v

    def _publish_pool(self, prompt: List[int], matched: int,
                      k: np.ndarray, v: np.ndarray,
                      lora) -> None:
        """Publish the page-aligned prompt prefix to the shared store and
        register it in the cluster directory. Adapter KV never enters
        either — it is not base-model KV."""
        ps = self.engine.cfg.page_size
        full = len(prompt) // ps
        if self.pool is not None and lora is None and full > matched // ps:
            try:
                self.pool.put(prompt, k[:, :full], v[:, :full])
                self.metrics["pool_exports"] += 1
            except (OSError, RuntimeError):
                self.metrics["pool_errors"] += 1
        if self.directory is not None and lora is None and full > 0 \
                and self.advertise_addr:
            try:
                self.directory.register(prompt[:full * ps],
                                        self.advertise_addr,
                                        slice_id=self.slice_id)
                self.metrics["dir_registers"] += 1
            except (OSError, RuntimeError, ValueError):
                pass  # the directory is an optimization, never a dependency

    def prefill(self, prompt: List[int],
                sampling: Optional[SamplingParams] = None,
                deadline: Optional[float] = None) -> KVBundle:
        """Run one prompt to its first token; export KV pages as one
        bundle (the legacy whole-blob handoff).

        ``deadline`` (absolute ``time.monotonic()``) aborts a long chunked
        prefill between chunks once the client's budget is spent — the
        pages recycle immediately instead of finishing a bundle nobody is
        waiting for. Raises the service-layer ``DeadlineExceeded`` so the
        server maps it to the structured wire code."""
        sampling = sampling or SamplingParams()
        one = dataclasses.replace(sampling, max_new_tokens=1)
        rid, matched = self._start_request(prompt, one)
        first = self._run_to_first(rid, deadline)
        req = self.engine.requests[rid]
        n_pages = pages_for_tokens(len(prompt), self.engine.cfg.page_size)
        k, v = self._export_pages(req, 0, n_pages)
        self.engine.release_request(rid)
        self._publish_pool(prompt, matched, k, v, sampling.lora)
        bundle = KVBundle(prompt=list(prompt), first_token=first,
                          k_data=k, v_data=v)
        self.metrics["bundles"] += 1
        self.metrics["bytes_out"] += bundle.nbytes
        return bundle

    def stream_meta(self, prompt: List[int],
                    stream_id: str) -> StreamMeta:
        cache = self.engine.cache
        return StreamMeta(
            stream_id=stream_id, prompt=list(prompt),
            n_pages=pages_for_tokens(len(prompt),
                                     self.engine.cfg.page_size),
            k_page_shape=tuple(cache.k_pages.shape[2:]),
            v_page_shape=tuple(cache.v_pages.shape[2:]),
            dtype=str(cache.k_pages.dtype),
            layers=int(cache.k_pages.shape[0]),
            page_size=self.engine.cfg.page_size)

    # hot_path
    def prefill_stream(self, prompt: List[int],
                       sampling: Optional[SamplingParams] = None,
                       *, transport, peer: str,
                       stream_id: Optional[str] = None,
                       deadline: Optional[float] = None,
                       layer_split: int = 0) -> PushResult:
        """Chunked, layer-overlapped prefill→decode push.

        META is sent before compute (the receiver can allocate pages
        early); each prefill chunk's newly-final full pages are exported
        and published AS the next chunk computes; the remaining pages, the
        first token, and FIN follow prefill completion. All SENDS happen
        on a dedicated sender thread — the prefill engine (and the
        server's pd_lock critical section around it) never blocks on the
        link. Returns when COMPUTE is done; the push drains behind
        (``PushResult.wait``). Push failures surface on the result, not as
        request failures — the caller decides bundle-fallback vs retry."""
        sampling = sampling or SamplingParams()
        one = dataclasses.replace(sampling, max_new_tokens=1)
        sid = stream_id or new_stream_id()
        meta = self.stream_meta(prompt, sid)
        res = PushResult(sid, meta)
        ps = self.engine.cfg.page_size
        split = layer_split or meta.layers
        q: "queue.Queue" = queue.Queue()
        pspan = trace.child(obs_names.SPAN_KVT_PUSH, stream_id=sid,
                            peer=peer, pages=meta.n_pages)

        def sender():
            send_s = 0.0   # pure link time, excluding waits on compute
            try:
                while True:
                    frame = q.get()
                    if frame is None:      # producer abort (deadline)
                        transport.send_one(peer, StreamFin(
                            sid, n_chunks=res.chunks, aborted=True,
                            error="prefill aborted"))
                        res._err = "prefill aborted before completion"
                        return
                    t0 = time.monotonic()
                    transport.send_one(peer, frame)
                    send_s += time.monotonic() - t0
                    if isinstance(frame, KVChunk):
                        res.nbytes += frame.nbytes
                        res.chunks += 1
                        REGISTRY.inc(obs_names.KVT_CHUNKS_TOTAL,
                                     direction="sent")
                    if isinstance(frame, StreamFin):
                        return
            except (StreamError, OSError) as e:
                res._err = str(e)
            finally:
                res.push_s = send_s
                if res.nbytes and res._err is None:
                    REGISTRY.inc(obs_names.KVT_STREAMS_TOTAL, outcome="ok")
                    REGISTRY.inc(obs_names.KVT_BYTES_TOTAL,
                                 float(res.nbytes), direction="sent",
                                 transport=transport.name)
                    # Measured link rate from THIS real transfer — what
                    # the router's transfer-cost scoring consumes.
                    transport.stats.observe(peer, res.nbytes, send_s)
                elif res._err is not None:
                    REGISTRY.inc(obs_names.KVT_STREAMS_TOTAL,
                                 outcome="error")
                pspan.end(outcome=res._err or "ok", bytes=res.nbytes)
                res._done.set()

        t = threading.Thread(target=sender, daemon=True,
                             name=f"kvpush-{sid}")
        t.start()
        q.put(meta)
        rid, matched = self._start_request(prompt, one)
        req = self.engine.requests[rid]
        exported = [0]    # pages fully exported so far
        seq = [0]
        # Retain the exported slabs when a pool/directory publish will
        # need the full prefix — re-exporting device→host a second time
        # would double the transfer AND stretch the server's pd_lock
        # critical section.
        publishing = ((self.pool is not None or self.directory is not None)
                      and sampling.lora is None and len(prompt) // ps > 0)
        slabs: List = []

        def publish_final_pages(r: Request) -> None:
            # Hold the LAST page group for the post-token tail: the final
            # page finalizes with the final prefill chunk (same instant
            # the first token's logits exist), and exporting it here
            # would queue its bytes AHEAD of StreamFirstToken — on an
            # in-order link that serializes every admission (which needs
            # the token) behind the full transfer, closing the
            # layer-sliced window for page-aligned prompts.
            done = min(r.prefill_pos // ps, meta.n_pages - 1)
            if done <= exported[0]:
                return
            k, v = self._export_pages(r, exported[0], done)
            if publishing:
                slabs.append((k, v))
            for ch in slab_to_chunks(meta, k, v, exported[0], seq[0],
                                     split):
                q.put(ch)
                seq[0] += 1
            self.metrics["stream_chunks"] += 1
            exported[0] = done

        try:
            first = self._run_to_first(rid, deadline,
                                       on_step=publish_final_pages)
        except Exception:
            q.put(None)    # structured abort to the receiver
            raise
        res.first_token = first
        # First token the moment compute ends — BEFORE the tail pages'
        # payload (the StreamFirstToken contract in kvtransfer.chunks).
        # Admission needs (coverage AND first token); queuing the token
        # behind the last chunk slab would serialize layer-sliced
        # admission behind the full transfer on any in-order link.
        q.put(StreamFirstToken(sid, first))
        # Remaining pages (the last prefill chunk's, incl. a partial
        # final page), then FIN.
        if exported[0] < meta.n_pages:
            k, v = self._export_pages(req, exported[0], meta.n_pages)
            if publishing:
                slabs.append((k, v))
            for ch in slab_to_chunks(meta, k, v, exported[0], seq[0],
                                     split):
                q.put(ch)
                seq[0] += 1
            exported[0] = meta.n_pages
        q.put(StreamFin(sid, n_chunks=seq[0]))
        # Pool/directory publish wants the page-aligned prefix —
        # assembled from the slabs already exported for the stream.
        if publishing and slabs:
            full = len(prompt) // ps
            k = np.concatenate([s[0] for s in slabs], axis=1)[:, :full]
            v = np.concatenate([s[1] for s in slabs], axis=1)[:, :full]
            self._publish_pool(prompt, matched, k, v, sampling.lora)
        self.engine.release_request(rid)
        self.metrics["streams"] += 1
        self.metrics["bytes_out"] += meta.nbytes()
        return res


class _StreamCommit:
    """Loop-thread bookkeeping for one in-flight inbound stream: the
    allocated pages and which staged cells already hit the device."""

    __slots__ = ("receiver", "pages", "committed", "t_first_commit",
                 "committed_map", "dispatched_layers", "admitted")

    def __init__(self, receiver):
        self.receiver = receiver
        self.pages: Optional[List[int]] = None
        self.committed = 0
        self.t_first_commit: Optional[float] = None
        # Layer-sliced admission state: which (layer, page) cells hit the
        # DEVICE (the dispatch watermark source), how many leading layers
        # the window chain already attended (commits below this are
        # clipped — a retransmitted slab must not zero the decode-token
        # KV the window pass wrote), and whether the row was admitted
        # (page ownership moved to the request).
        self.committed_map = None          # np.bool_ [L, n_pages]
        self.dispatched_layers = 0
        self.admitted = False


class DecodeWorker:
    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None):
        cfg = dataclasses.replace(cfg, mode="decode", enable_radix_cache=False)
        self.engine = Engine(cfg, params=params, mesh=mesh)
        self.metrics = {"bundles": 0, "bytes_in": 0, "streams_in": 0,
                        "stream_commits": 0, "stream_errors": 0}
        # Serializes the device page-pool swap against any OTHER committer
        # (the engine loop thread is the only sanctioned one — the lock
        # makes a violation visible instead of silently corrupting KV) and
        # feeds the pd_lock hold-time histogram: the satellite contract is
        # copy OUTSIDE this lock, commit alone inside it.
        self._commit_lock = named_lock("engine.pd_commit")
        # Loop-thread-confined: stream_id → _StreamCommit. TTL backstop:
        # a stream nobody finalizes (abandoned push, dead consumer) must
        # release its pages instead of holding KV capacity forever.
        self._stream_commits: Dict[str, _StreamCommit] = {}
        self.stream_ttl_s = 120.0
        # Layer-sliced admission: jitted forward_paged_window programs
        # keyed (layer_lo, layer_hi, B) and the per-bucket LM head.
        self._window_fns: Dict = {}
        self._head_fns: Dict = {}

    # ---- shared commit primitive ----

    def _commit_pages(self, ids: jnp.ndarray, k_dev, v_dev,
                      layer_lo: Optional[int] = None,
                      layer_hi: Optional[int] = None) -> None:
        """Swap staged K/V into the device page pool. The staging
        (host→device conversion) happened in the CALLER, outside the
        lock; only the functional pool swap is serialized."""
        eng = self.engine
        t0 = time.perf_counter()
        with self._commit_lock:
            if layer_lo is None:
                k_pages = eng.cache.k_pages.at[:, ids].set(k_dev)
                v_pages = eng.cache.v_pages.at[:, ids].set(v_dev)
            else:
                k_pages = eng.cache.k_pages.at[layer_lo:layer_hi, ids].set(k_dev)
                v_pages = eng.cache.v_pages.at[layer_lo:layer_hi, ids].set(v_dev)
            eng.cache = PagedKVCache(k_pages=k_pages, v_pages=v_pages)
        REGISTRY.observe(obs_names.PD_LOCK_HOLD_SECONDS,
                         time.perf_counter() - t0, lock="pd_commit")

    # ---- whole-bundle import ----

    # hot_path
    def inject(self, bundle: KVBundle,
               sampling: Optional[SamplingParams] = None) -> int:
        """Import a KV bundle and start decoding it. Returns the request id.
        The first token is accounted as output[0] (already produced).

        The page-pool import (the on-device half of the prefill→decode KV
        handoff) gets its own ``pd.kv_handoff`` span under the ambient
        request span. The host→device staging happens BEFORE the commit
        lock; only the page-table swap holds it (hold time lands in the
        rbg_pd_lock_hold_seconds histogram)."""
        sampling = sampling or SamplingParams()
        eng = self.engine
        prompt = bundle.prompt
        eng._check_prompt(prompt)
        # Before alloc — a raise must not leak pages.
        eng._grammar_check(sampling)
        n_pages = bundle.k_data.shape[1]
        need = pages_for_tokens(len(prompt) + 1, eng.cfg.page_size)
        pages = eng._alloc(need)
        if pages is None:
            raise RuntimeError("decode engine out of KV pages")
        # Context-manager form: a raise in the page import must still end
        # the span or the trace finalizes incomplete.
        with trace.child(obs_names.SPAN_PD_KV_HANDOFF,
                         bytes=bundle.nbytes, pages=int(n_pages)):
            ids = jnp.asarray(pages[:n_pages], jnp.int32)
            # Staging (host→device dtype conversion) outside the lock.
            k_dev = jnp.asarray(bundle.k_data, eng.cache.k_pages.dtype)
            v_dev = jnp.asarray(bundle.v_data, eng.cache.v_pages.dtype)
            self._commit_pages(ids, k_dev, v_dev)
        try:
            rid = self._admit_row(prompt, bundle.first_token, pages,
                                  sampling)
        except Exception:
            eng.allocator.release(pages)
            raise
        self.metrics["bundles"] += 1
        self.metrics["bytes_in"] += bundle.nbytes
        return rid

    def _admit_row(self, prompt: List[int], first_token: int,
                   pages: List[int],
                   sampling: SamplingParams) -> int:
        """Post-KV-import admission shared by bundle and stream paths:
        grammar fold-in, request construction, finished-at-inject
        handling. The caller releases pages on a raise."""
        eng = self.engine
        lora_idx = eng._resolve_lora(sampling)
        req = Request(prompt, sampling)
        req.lora_idx = lora_idx
        g = eng._grammar_for(sampling)
        if g is not None:
            # The first token was sampled prefill-side under the grammar
            # mask — fold it in so decode continues from the right state.
            # This must cover ALL THREE constraint kinds: a json_mode
            # request without req.grammar used to crash the decode batch
            # (advance_token on a None grammar), and regex/json_schema
            # requests silently decoded UNCONSTRAINED.
            nxt = g.advance_token(g.initial(), first_token)
            if nxt is None:
                # A grammar-wired prefill can't produce this; it means the
                # prefill peer ignored the constraint (mixed-version
                # deploy). Reject rather than emit corrupt "constrained"
                # output.
                raise ValueError(
                    f"first token {first_token} violates the "
                    "request's grammar constraint — prefill peer ignored "
                    "json_mode/regex/json_schema?")
            req.grammar = g
            req.gstate = nxt
        req.state = "running"
        req.pages = pages
        req.seq_len = len(prompt)
        req.prefill_pos = len(prompt)
        req.output = [first_token]
        req.last_token = first_token
        req.t_first = time.perf_counter()
        eng.requests[req.id] = req
        eng.running.append(req)
        # Already complete (max_new_tokens == 1 or stop token hit): finish
        # now so its pages recycle.
        if (len(req.output) >= sampling.max_new_tokens
                or (sampling.stop_token is not None
                    and first_token == sampling.stop_token)):
            eng._finish(req)
        return req.id

    # ---- streaming import (engine loop thread only) ----

    def begin_stream(self, receiver) -> None:
        """Start committing a stream's chunks as they arrive. Loop-thread
        only (the engine single-writer contract)."""
        sid = receiver.stream_id
        if sid not in self._stream_commits:
            self._stream_commits[sid] = _StreamCommit(receiver)

    # hot_path
    def pump_streams(self) -> int:
        """Write newly-arrived chunks of every watched stream into the
        device page table. Loop-thread only. Returns cells committed."""
        eng = self.engine
        done = 0
        now = time.monotonic()
        for sid in list(self._stream_commits):
            sc = self._stream_commits[sid]
            rx = sc.receiver
            if now - rx.t_open > self.stream_ttl_s:
                rx.fail("stream expired unconsumed (TTL)")
            if rx.error() is not None:
                # Structured failure: recycle any pages; the waiter (the
                # decode_stream handler) surfaces the error. An ADMITTED
                # row's pages belong to the request (the layer-sliced
                # window chain cancels it and releases them there) —
                # releasing here too would double-free the page ids.
                if sc.pages is not None and not sc.admitted:
                    eng.allocator.release(sc.pages)
                del self._stream_commits[sid]
                self.metrics["stream_errors"] += 1
                REGISTRY.inc(obs_names.KVT_STREAMS_TOTAL,
                             outcome="recv_error")
                continue
            a = rx.assembler
            if a is None:
                continue
            if sc.pages is None:
                need = pages_for_tokens(len(a.meta.prompt) + 1,
                                        eng.cfg.page_size)
                pages = eng._alloc(need)
                if pages is None:
                    continue   # retry when pages free up
                sc.pages = pages
            cells = rx.drain_uncommitted()
            if not cells:
                continue
            done += self._commit_cells(sc, cells)
        return done

    def _commit_cells(self, sc: _StreamCommit, cells) -> int:
        """Grouped device writes for staged (layer, page) cells. The host
        slice + device staging happen outside the commit lock."""
        rx = sc.receiver
        a = rx.assembler
        eng = self.engine
        if sc.t_first_commit is None:
            sc.t_first_commit = time.perf_counter()
        if sc.committed_map is None:
            sc.committed_map = np.zeros((a.meta.layers, a.meta.n_pages),
                                        bool)
        with trace.child(obs_names.SPAN_KVT_COMMIT,
                         stream_id=rx.stream_id, cells=len(cells)):
            for (llo, lhi, plo, phi) in cells:
                # Clip below the dispatch watermark: layers the window
                # chain already attended carry the decode token's KV at
                # slot len(prompt) — a lossy link's retransmitted slab
                # (re-staged by the assembler on partial overlap) must not
                # zero it. Everything below the watermark is on device
                # already (dispatch REQUIRES the watermark), so skipping
                # is lossless.
                llo = max(llo, sc.dispatched_layers)
                if llo >= lhi:
                    continue
                ids = jnp.asarray(sc.pages[plo:phi], jnp.int32)
                k_dev = jnp.asarray(a.k[llo:lhi, plo:phi],
                                    eng.cache.k_pages.dtype)
                v_dev = jnp.asarray(a.v[llo:lhi, plo:phi],
                                    eng.cache.v_pages.dtype)
                self._commit_pages(ids, k_dev, v_dev, llo, lhi)
                sc.committed_map[llo:lhi, plo:phi] = True
                self.metrics["stream_commits"] += 1
        return len(cells)

    def finalize_stream(self, receiver,
                        sampling: Optional[SamplingParams] = None) -> int:
        """Admit a coverage-complete stream as a running decode row. Loop
        thread only; the receiver must be ready() (the caller waited).
        Flushes any cells not yet committed, then admits — the row starts
        decoding even while the stream's FIN is still in flight."""
        sampling = sampling or SamplingParams()
        eng = self.engine
        rx = receiver
        if rx.error() is not None:
            raise StreamError(rx.error())
        a = rx.assembler
        if a is None or not a.ready():
            raise StreamError(
                f"stream {rx.stream_id} not ready at finalize")
        prompt = list(a.meta.prompt)
        try:
            eng._check_prompt(prompt)
            eng._grammar_check(sampling)
        except Exception:
            # Wire-supplied meta can be garbage — recycle any pages the
            # pump already allocated for it before failing the request.
            self.abandon_stream(rx)
            raise
        if rx.t_first_step is None:
            # Decode stopped waiting on the transfer plane here: the
            # admission decision is made and everything after (page
            # flush, inject scatter, the first step) is engine cost, not
            # plane wait. Stamping at the decision — not when the first
            # step's events surface — keeps the kv_stream_overlap
            # comparison honest when FIN rides the same link flush as
            # the final data chunk.
            rx.t_first_step = time.monotonic()
        self.begin_stream(rx)
        sc = self._stream_commits[rx.stream_id]
        if sc.pages is None:
            need = pages_for_tokens(len(prompt) + 1, eng.cfg.page_size)
            sc.pages = eng._alloc(need)
            if sc.pages is None:
                del self._stream_commits[rx.stream_id]
                # StreamError (not RuntimeError): the wire code lets the
                # router retry this row on a sibling in bundle mode — the
                # pushed KV cannot be admitted here.
                raise StreamError("decode engine out of KV pages")
        cells = rx.drain_uncommitted()
        if cells:
            self._commit_cells(sc, cells)
        pages = sc.pages
        del self._stream_commits[rx.stream_id]
        try:
            rid = self._admit_row(prompt, int(a.first_token), pages,
                                  sampling)
        except Exception:
            eng.allocator.release(pages)
            raise
        self.metrics["streams_in"] += 1
        self.metrics["bytes_in"] += a.bytes_seen
        REGISTRY.inc(obs_names.KVT_BYTES_TOTAL, float(a.bytes_seen),
                     direction="recv", transport="stream")
        return rid

    # ---- layer-sliced admission (engine loop thread only) ----

    def _device_layer_coverage(self, sc: _StreamCommit) -> int:
        """Leading layers whose every page cell hit the DEVICE — the
        dispatch watermark (host assembly coverage is necessary but not
        sufficient: the window must attend committed pages)."""
        m = sc.committed_map
        if m is None:
            return 0
        return int(np.cumprod(m.all(axis=1)).sum())

    def _get_window_fn(self, lo: int, hi: int, B: int):
        """Jitted layer-window forward, cached per (layer_lo, layer_hi,
        bucket). Pools are donated: each window consumes the pool snapshot
        it was handed and returns the next one."""
        key = (lo, hi, B)
        fn = self._window_fns.get(key)
        if fn is None:
            import functools

            from rbg_tpu.models.llama import forward_paged_window
            eng = self.engine
            base = functools.partial(forward_paged_window, eng.params,
                                     eng.mcfg, lo, hi,
                                     use_pallas=eng.cfg.use_pallas)

            def window(x, pos, mask, kvl, table, k_pages, v_pages,
                       k_scales, v_scales):
                return base(x, pos, mask, kvl, table, k_pages, v_pages,
                            k_scales=k_scales, v_scales=v_scales)

            window.__name__ = obs_names.PROGRAM_PD_WINDOW   # jitwatch catalog
            donate = (5, 6, 7, 8) if eng.cache.quantized else (5, 6)
            fn = jax.jit(window, donate_argnums=donate)
            self._window_fns[key] = fn
        return fn

    def _get_head_fn(self, B: int):
        fn = self._head_fns.get(B)
        if fn is None:
            from rbg_tpu.models.llama import _head
            eng = self.engine

            def head(x):
                return _head(eng.params, eng.mcfg, x)

            head.__name__ = obs_names.PROGRAM_PD_HEAD   # jitwatch catalog
            fn = jax.jit(head)
            self._head_fns[B] = fn
        return fn

    def _wait_layer_watermark(self, sc: _StreamCommit, hi: int,
                              deadline: Optional[float]) -> None:
        """Block (pumping commits) until the first ``hi`` layers are fully
        on device. A layer missing its watermark degrades to waiting — the
        same wait the full-coverage path would pay — bounded by
        ``deadline`` and the receiver's error state, never a wedge."""
        rx = sc.receiver
        while self._device_layer_coverage(sc) < hi:
            if rx.error() is not None:
                raise StreamError(rx.error())
            self.pump_streams()
            if self._device_layer_coverage(sc) >= hi:
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise StreamError(
                    f"stream {rx.stream_id}: layer watermark {hi} not "
                    f"reached before deadline (device coverage "
                    f"{self._device_layer_coverage(sc)})")
            time.sleep(0.0002)

    def finalize_stream_layer_sliced(self, receiver,
                                     sampling: Optional[SamplingParams]
                                     = None,
                                     min_layers: int = 1,
                                     deadline: Optional[float] = None
                                     ) -> int:
        """Admit a stream at layer-``min_layers`` coverage — BEFORE the
        tail layers land — and run the first decode step as a chain of
        layer-windowed forward passes, each dispatched the moment its
        layers' pages are on device. The decode step overlaps the
        transfer tail instead of waiting it out (the TTFD cut on top of
        chunk-streamed admission). Loop thread only.

        The chain reproduces the fused decode program's first iteration
        exactly: same padded bucket, same write mask, same key schedule
        (fold_in(row_key, seq_len + 1)), same grammar-mask/penalty/sampler
        composition — its emitted token is bit-identical to the token the
        fused path would have produced, and the KV it writes at slot
        ``len(prompt)`` is the KV the fused path would have written.
        Subsequent tokens ride the normal fused path."""
        sampling = sampling or SamplingParams()
        eng = self.engine
        rx = receiver
        if rx.error() is not None:
            raise StreamError(rx.error())
        if sampling.lora is not None:
            # The layer-window forward has no adapter path (same exclusion
            # as the unified step) — callers route lora rows to the
            # full-coverage wait.
            raise StreamError(
                "layer-sliced admission does not support lora requests")
        a = rx.assembler
        if a is None or not a.ready_layers(min_layers):
            raise StreamError(
                f"stream {rx.stream_id} not layer-ready at layer-sliced "
                f"finalize (need {min_layers} layers)")
        prompt = list(a.meta.prompt)
        try:
            eng._check_prompt(prompt)
            eng._grammar_check(sampling)
        except Exception:
            self.abandon_stream(rx)
            raise
        self.begin_stream(rx)
        sid = rx.stream_id
        sc = self._stream_commits[sid]
        if sc.pages is None:
            need = pages_for_tokens(len(prompt) + 1, eng.cfg.page_size)
            sc.pages = eng._alloc(need)
            if sc.pages is None:
                del self._stream_commits[sid]
                raise StreamError("decode engine out of KV pages")
        cells = rx.drain_uncommitted()
        if cells:
            self._commit_cells(sc, cells)
        pages = sc.pages
        try:
            rid = self._admit_row(prompt, int(a.first_token), pages,
                                  sampling)
        except Exception:
            eng.allocator.release(pages)
            del self._stream_commits[sid]
            raise
        # Page ownership moved to the request — a later stream error must
        # not release them a second time (pump_streams checks this flag).
        sc.admitted = True
        layers_at_admit = a.layer_coverage()
        rx.layers_at_admit = layers_at_admit
        rx.total_layers = int(a.meta.layers)
        self.metrics["streams_in"] += 1
        self.metrics["bytes_in"] += a.bytes_seen
        REGISTRY.inc(obs_names.KVT_BYTES_TOTAL, float(a.bytes_seen),
                     direction="recv", transport="stream")
        REGISTRY.inc(obs_names.KVT_LAYER_ADMIT_TOTAL)
        REGISTRY.observe(obs_names.KVT_LAYER_ADMIT_COVERAGE_LAYERS,
                         float(layers_at_admit))
        req = eng.requests.get(rid)
        if req is None or req.state != "running":
            # Finished at inject (max_new_tokens == 1 / stop token): its
            # pages already recycled — stop committing into them NOW.
            del self._stream_commits[sid]
            return rid
        try:
            # The chain IS the row's first decode step — stamp it here
            # (before FIN can land) so overlap accounting credits the
            # decode work started under the transfer tail.
            receiver.t_first_step = time.monotonic()
            with trace.child(obs_names.SPAN_PD_LAYER_SLICED_STEP,
                             stream_id=sid,
                             layers_at_admit=layers_at_admit):
                self._layer_sliced_first_step(sc, req, min_layers,
                                              deadline)
        except BaseException:
            self._stream_commits.pop(sid, None)
            eng.cancel_request(rid)
            raise
        # Every layer is dispatched (and therefore committed) — the only
        # frames still in flight are duplicates/FIN; drop the watch.
        del self._stream_commits[sid]
        return rid

    def _layer_sliced_first_step(self, sc: _StreamCommit, req,
                                 min_layers: int,
                                 deadline: Optional[float]) -> None:
        """The layer-windowed decode step for a just-admitted row: embed →
        [wait watermark → window forward] per layer window → head →
        sample → emit (deferred). Mirrors the fused program's first
        iteration; see ``finalize_stream_layer_sliced``."""
        from rbg_tpu.engine.sampler import NEG_INF, row_keys, step_keys
        eng = self.engine
        L = int(eng.cache.k_pages.shape[0])
        win = max(1, int(min_layers))
        B = eng._bucket(1)
        P = eng.cfg.max_pages_per_seq
        tok = np.zeros(B, np.int32)
        pos = np.zeros(B, np.int32)
        kvl = np.zeros(B, np.int32)
        mask = np.zeros((B, 1), bool)
        limit = np.zeros(B, np.int32)
        table = np.zeros((B, P), np.int32)
        tok[0] = req.last_token
        pos[0] = req.seq_len
        kvl[0] = req.seq_len + 1
        mask[0, 0] = True
        limit[0] = req.max_len()
        table[0, :len(req.pages)] = req.pages
        temps, ks, tps, mps, seeds, rids, pen, lp, tpmp = \
            eng._sampling_rows([req], B)
        write_ok = jnp.asarray(mask & (pos < limit)[:, None])  # [B, 1]
        pos_d = jnp.asarray(pos)
        kvl_d = jnp.asarray(kvl)
        table_d = jnp.asarray(table)
        # Embedding gather + cast — pure data movement, bit-exact whether
        # traced or eager, so it can live outside the window programs.
        x = eng.params["embed"].astype(eng.mcfg.jax_dtype)[
            jnp.asarray(tok)[:, None]]                         # [B, 1, D]
        for lo in range(0, L, win):
            hi = min(lo + win, L)
            self._wait_layer_watermark(sc, hi, deadline)
            fn = self._get_window_fn(lo, hi, B)
            cache = eng.cache
            x, kp, vp, ksc, vsc = fn(x, pos_d[:, None], write_ok, kvl_d,
                                     table_d, cache.k_pages,
                                     cache.v_pages, cache.k_scales,
                                     cache.v_scales)
            with self._commit_lock:
                eng.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                         k_scales=ksc, v_scales=vsc)
            sc.dispatched_layers = hi
        lg = self._get_head_fn(B)(x)[:, 0, :]                  # [B, V]
        if req.gstate is not None:
            # Grammar mask before sampling — the host-synced path's exact
            # order (penalties apply inside sample()).
            gm = np.ones((B, eng.mcfg.vocab_size), bool)
            gm[0] = eng._gmask(req.grammar, req.gstate)
            lg = jnp.where(jnp.asarray(gm), lg, NEG_INF)
        # Key by the OUTPUT position (seq_len + 1) — the fused program's
        # key schedule for the first decode token.
        keys = step_keys(row_keys(seeds, eng._sample_base, rids),
                         jnp.asarray(pos + 1))
        args = [lg, keys, jnp.asarray(temps), jnp.asarray(ks),
                jnp.asarray(tps), jnp.asarray(mps)]
        if pen:
            pmask, oc, rep, pres, freq = eng._penalty_rows([req], B)
            np.add.at(oc[0], np.asarray(req.output, np.int64), 1)
            args += [pmask, jnp.asarray(oc), rep, pres, freq]
        toks, lps = eng._get_sampler(pen, lp, tpmp)(*args)
        tok_out = int(np.asarray(toks)[0])
        lp_val = (float(np.asarray(lps)[0])
                  if lps is not None and req.sampling.logprobs else None)
        req.seq_len += 1
        eng.metrics["decode_tokens"] += 1
        # Deferred emission: the event surfaces from the engine's next
        # step() drain, exactly like a unified-step decode token.
        eng._deferred_events.append(eng._emit(req, tok_out, lp_val))

    def warm_layer_sliced(self, min_layers: int) -> float:
        """Compile the layer-window chain (window programs, head, default
        sampler) before traffic — all writes masked off, so the live pool
        round-trips unchanged through the donated calls."""
        eng = self.engine
        t0 = time.perf_counter()
        L = int(eng.cache.k_pages.shape[0])
        win = max(1, int(min_layers))
        B = eng._bucket(1)
        P = eng.cfg.max_pages_per_seq
        x = eng.params["embed"].astype(eng.mcfg.jax_dtype)[
            jnp.zeros((B, 1), jnp.int32)]
        pos = jnp.zeros((B, 1), jnp.int32)
        mask = jnp.zeros((B, 1), bool)
        kvl = jnp.zeros(B, jnp.int32)
        table = jnp.zeros((B, P), jnp.int32)
        for lo in range(0, L, win):
            hi = min(lo + win, L)
            fn = self._get_window_fn(lo, hi, B)
            cache = eng.cache
            x, kp, vp, ksc, vsc = fn(x, pos, mask, kvl, table,
                                     cache.k_pages, cache.v_pages,
                                     cache.k_scales, cache.v_scales)
            with self._commit_lock:
                eng.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                         k_scales=ksc, v_scales=vsc)
        self._get_head_fn(B)(x).block_until_ready()
        # The first-step sampler: _layer_sliced_first_step samples on the
        # HOST path even on a decode-role engine (the fused scan only
        # takes over from the second token). The jitwatch sentry caught
        # this warmer silently not covering it — the compile landed
        # mid-measurement the first time layer-sliced admission engaged.
        from rbg_tpu.engine.sampler import row_keys, step_keys
        temps, ks, tps, mps, seeds, rids, _, _, _ = eng._sampling_rows([], B)
        keys = step_keys(row_keys(seeds, eng._sample_base, rids),
                         jnp.zeros(B, jnp.int32))
        for tpmp in (False, True):
            toks, _ = eng._get_sampler(False, False, tpmp)(
                jnp.zeros((B, eng.mcfg.vocab_size), jnp.float32), keys,
                jnp.asarray(temps), jnp.asarray(ks), jnp.asarray(tps),
                jnp.asarray(mps))
            toks.block_until_ready()
        return time.perf_counter() - t0

    def abandon_stream(self, receiver) -> None:
        """Drop a watched stream (deadline/cancel before admission) —
        pages recycle. Loop thread only."""
        sc = self._stream_commits.pop(receiver.stream_id, None)
        if sc is not None and sc.pages is not None:
            self.engine.allocator.release(sc.pages)


class PDPair:
    """In-process prefill+decode pair — the single-host PD-disagg unit the
    bench exercises (BASELINE configs 3-4)."""

    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None):
        self.prefill = PrefillWorker(cfg, params=params, mesh=mesh)
        # Decode shares weights with prefill (same chip in-process).
        self.decode = DecodeWorker(cfg, params=self.prefill.engine.params, mesh=mesh)

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None,
                 collect_ttft: bool = False):
        sampling = sampling or SamplingParams()
        outputs: Dict[int, List[int]] = {}
        ttft: List[float] = []
        order = []
        for p in prompts:
            t0 = time.perf_counter()
            bundle = self.prefill.prefill(p, sampling)
            rid = self.decode.inject(bundle, sampling)
            ttft.append(time.perf_counter() - t0)
            outputs[rid] = [bundle.first_token]
            order.append(rid)
        while self.decode.engine.has_work():
            for ev in self.decode.engine.step():
                if ev.request_id in outputs:
                    outputs[ev.request_id].append(ev.token)
        result = [outputs[r] for r in order]
        return (result, ttft) if collect_ttft else result


class PDStreamPair:
    """In-process PD pair over an explicit ``kvtransfer`` transport —
    the chunked/overlapped twin of ``PDPair`` the bench A/Bs and the
    slow-link stress drill drive. ``stream=False`` sends the SAME frames
    whole (every chunk after prefill completes, admission only at FIN):
    the whole-bundle baseline measured over the identical link."""

    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None, transport=None, layer_split: int = 0,
                 admit_layers: int = 0):
        from rbg_tpu.kvtransfer.transport import InProcTransport

        self.prefill = PrefillWorker(cfg, params=params, mesh=mesh)
        self.decode = DecodeWorker(cfg, params=self.prefill.engine.params,
                                   mesh=mesh)
        self.transport = transport or InProcTransport()
        self.layer_split = layer_split
        # > 0: admit at layer-k coverage and run the first decode step as
        # a layer-windowed chain overlapping the transfer tail. Only
        # effective with a layer_split fine enough to stream layers
        # separately (layer_split == 0 sends all layers per chunk — there
        # is no tail to overlap).
        self.admit_layers = int(admit_layers)

    def generate_one(self, prompt: List[int],
                     sampling: Optional[SamplingParams] = None,
                     stream: bool = True, recv_timeout: float = 30.0,
                     max_retries: int = 1) -> dict:
        """One request through the transfer plane. Returns a timing dict:
        tokens, t_first_decode (request start → first DECODE token — the
        stall the plane shrinks), admit_lead_s, retries."""
        from rbg_tpu.kvtransfer.chunks import bundle_to_frames
        from rbg_tpu.kvtransfer.stream import KVStreamReceiver

        sampling = sampling or SamplingParams()
        t0 = time.perf_counter()
        last_err = None
        for attempt in range(max_retries + 1):
            sid = new_stream_id()
            rx = KVStreamReceiver(sid)
            rx_thread = threading.Thread(
                target=rx.pump, args=(self.transport,),
                kwargs={"timeout": recv_timeout}, daemon=True,
                name=f"kvrecv-{sid}")
            rx_thread.start()
            if stream:
                res = self.prefill.prefill_stream(
                    prompt, sampling, transport=self.transport, peer="",
                    stream_id=sid, layer_split=self.layer_split)
                first_token = res.first_token
            else:
                bundle = self.prefill.prefill(prompt, sampling)
                first_token = bundle.first_token
                meta = self.prefill.stream_meta(prompt, sid)
                frames = bundle_to_frames(meta, bundle.k_data,
                                          bundle.v_data,
                                          bundle.first_token,
                                          self.layer_split)
                threading.Thread(target=self.transport.send_chunks,
                                 args=("", frames), daemon=True,
                                 name=f"kvsend-{sid}").start()
            # Drive commits while the stream lands; admit at coverage
            # (stream arm) / at FIN (whole-bundle semantics: ready implies
            # all data, and FIN follows immediately in this arm anyway).
            self.decode.begin_stream(rx)
            deadline = time.monotonic() + recv_timeout
            rid = None
            while rid is None:
                if rx.error() is not None:
                    last_err = rx.error()
                    self.decode.abandon_stream(rx)
                    break
                self.decode.pump_streams()
                if (self.admit_layers > 0 and stream
                        and sampling.lora is None and not rx.ready()
                        and rx.ready_layers(self.admit_layers)):
                    # Layer-sliced early admission: layer-k coverage is in
                    # but full coverage is not — start decoding under the
                    # transfer tail. (Full coverage already in: the plain
                    # finalize below is strictly cheaper.) A mid-chain
                    # stream failure cancels the row before any token is
                    # emitted, so falling into the retry loop stays
                    # token-exact.
                    try:
                        rid = self.decode.finalize_stream_layer_sliced(
                            rx, sampling, min_layers=self.admit_layers,
                            deadline=deadline)
                    except StreamError as e:
                        last_err = str(e)
                    break
                if rx.ready() and (stream or rx.t_fin is not None):
                    rid = self.decode.finalize_stream(rx, sampling)
                    break
                if time.monotonic() >= deadline:
                    self.decode.abandon_stream(rx)
                    raise StreamError(
                        f"stream {sid} never became ready")
                time.sleep(0.0002)
            if rid is None:
                continue   # retry (token-exact: decode never started)
            tokens = [first_token]
            t_first_decode = None
            while self.decode.engine.has_work():
                for ev in self.decode.engine.step():
                    if ev.request_id == rid:
                        if t_first_decode is None:
                            t_first_decode = time.perf_counter() - t0
                            if rx.t_first_step is None:
                                # Layer-sliced rows stamped this at the
                                # window chain's start already.
                                rx.t_first_step = time.monotonic()
                        tokens.append(ev.token)
            rx_thread.join(timeout=recv_timeout)
            return {"tokens": tokens, "t_first_decode": t_first_decode,
                    "admit_lead_s": rx.admit_lead_s(),
                    # Overlap: the first decode step landed BEFORE the
                    # stream's close frame — decode started while the
                    # transfer plane was still moving this row's stream.
                    "overlap": (rx.t_first_step is not None
                                and rx.t_fin is not None
                                and rx.t_first_step < rx.t_fin),
                    "retries": attempt, "stream_id": sid,
                    "layers_at_admit": rx.layers_at_admit,
                    "total_layers": rx.total_layers,
                    "bytes": rx.assembler.bytes_seen if rx.assembler
                    else 0}
        raise StreamError(
            f"stream failed after {max_retries + 1} attempts: {last_err}")

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None,
                 stream: bool = True, **kw) -> List[List[int]]:
        return [self.generate_one(p, sampling, stream=stream, **kw)["tokens"]
                for p in prompts]
