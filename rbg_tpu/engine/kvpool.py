"""Shared cross-request KV pool — the Mooncake-store analog.

Reference context: the reference's flagship ecosystem path is a SHARED,
cross-request KV store with prefix reuse (``keps/74-mooncake-integration/
README.md``, ``examples/inference/ecosystem/mooncake/mooncake-store/
pd-disagg-kvcache-reuse-with-mooncake.yaml``): prefill nodes publish
computed prefix KV; later requests with a common prefix fetch it instead of
recomputing, across ALL prefill replicas (the per-engine radix cache only
reuses within one process).

Pieces:

* ``KVPoolStore``  — host-memory page store: a token-trie over page-aligned
  prefixes (one node per page), LRU-evicted against a byte budget. Values
  are numpy ``[L, page, KV, hd]`` page pairs — host RAM is the pool's
  medium (Mooncake's DRAM/SSD tier analog); the TPU HBM pool stays private
  to each engine.
* ``KVPoolServer`` — the ``kv-pool`` role's process: TCP service on the
  plane's discovery fabric (``python -m rbg_tpu.engine.kvpool``), ops
  ``pool_match`` / ``pool_put`` / ``pool_stats`` over the same length-
  prefixed wire protocol the PD path uses.
* ``KVPoolClient`` — used by prefill workers: consult before computing,
  export after.

Transfer format matches ``pd.KVBundle`` framing: one contiguous K block +
one V block per message (``protocol.send_msg`` binary lanes).

Wire security (flag-gated, VERDICT r4 #6): ``--auth-token`` (env
``RBG_DATA_TOKEN``) requires a shared bearer token on every data op
(``health`` stays open for liveness probes), and ``--cert-dir`` wraps the
listener in TLS using the same self-signed CA bootstrap as the admin
wire (``runtime/tlsutil.ensure_certs``). Clients pass ``token=`` /
``ca_path=``. Without the flags the wire is open — the NetworkPolicy in
``deploy/k8s/rbg-tpu.yaml`` is then the only fence (documented there).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import ssl
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from rbg_tpu.engine.protocol import recv_msg, send_msg, token_ok
from rbg_tpu.kvtransfer.chunks import payload_checksum
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_lock
from rbg_tpu.utils.racetrace import guard as _race_guard


class _Node:
    __slots__ = ("key", "k", "v", "children", "parent", "last_used",
                 "nbytes", "dirkey", "hits", "crc")

    def __init__(self, key: Tuple[int, ...], parent):
        self.key = key                    # page_size tokens
        self.k: Optional[np.ndarray] = None   # [L, page, KV, hd]
        self.v: Optional[np.ndarray] = None
        # Payload checksum minted when the page payload was stored (None
        # until then) — verified on every match/extend so bytes that
        # rotted while resident (or were poisoned over the wire) are
        # dropped as a miss instead of served (spill→promote rides this).
        self.crc: Optional[int] = None
        self.children: Dict[int, "_Node"] = {}
        self.parent = parent
        self.last_used = time.monotonic()
        self.nbytes = 0
        # Directory hash-chain key of the prefix ending at this node
        # (chunks.prefix_keys convention) — eviction invalidates it.
        self.dirkey: str = ""
        # Hotness: payload matches through this node. Eviction is
        # LRU-by-hotness — coldest (fewest hits) pages go first.
        self.hits = 0

    @property
    def placeholder(self) -> bool:
        """Path-only node: a deeper tier (the device radix cache) still
        holds this page, so the trie keeps the route to payload pages
        below it without holding data itself (the host tier receives
        DEEP pages first — radix eviction is leaf-first)."""
        return self.k is None


@_race_guard
class KVPoolStore:
    """Page-granular prefix trie with LRU byte-budget eviction."""

    def __init__(self, page_size: int, max_bytes: int = 1 << 30,
                 directory=None):
        self.page_size = page_size
        self.max_bytes = max_bytes
        self.root = _Node((), None)  # guarded_by[engine.kvpool]
        self.bytes = 0  # guarded_by[engine.kvpool]
        self._lock = named_lock("engine.kvpool")
        # Cluster prefix directory living NEXT to the pool (the kv-pool
        # server hosts both): evicting a prefix here invalidates its
        # directory keys, so a lookup can never return an evicted prefix.
        self.directory = directory
        # Backend whose claims this store's evictions invalidate. Empty
        # = key-wide (the shared cluster pool, sole holder registry);
        # a per-replica host tier (kvtier.wire_directory) sets its own
        # address so its byte-budget eviction cannot wipe a sibling
        # replica's claim for the same content-hashed key.
        self.owner_backend = ""
        # guarded_by[engine.kvpool]
        self.metrics = {"hits": 0, "misses": 0, "hit_tokens": 0,
                        "put_pages": 0, "evicted_pages": 0, "pages": 0}

    # ---- lookup ----

    def match(self, tokens: List[int]) -> Tuple[int, Optional[np.ndarray],
                                                Optional[np.ndarray]]:
        """Longest page-aligned stored prefix of ``tokens``. Returns
        (matched_tokens, k [L, n_pages, page, KV, hd], v) — None arrays on
        a miss."""
        ps = self.page_size
        with self._lock:
            node = self.root
            ks, vs, run = [], [], []
            i, n = 0, (len(tokens) // ps) * ps
            now = time.monotonic()
            while i < n:
                child = node.children.get(tuple(tokens[i:i + ps]))
                if child is None or child.placeholder:
                    break   # payload run ends (missing or path-only node)
                child.last_used = now
                child.hits += 1
                ks.append(child.k)
                vs.append(child.v)
                run.append(child)
                i += ps
                node = child
            if not ks:
                self.metrics["misses"] += 1
                return 0, None, None
            self.metrics["hits"] += 1
            self.metrics["hit_tokens"] += i
        # The payload copy happens OUTSIDE the lock: stored arrays are
        # immutable (eviction only drops references; our refs keep them
        # alive), and a multi-MB np.stack under the global lock would
        # serialize every other replica's match/put behind it. Checksum
        # verification rides the same rationale.
        good = self._verify_run(run, ks, vs)
        if good == 0:
            return 0, None, None
        return (good * ps, np.stack(ks[:good], axis=1),
                np.stack(vs[:good], axis=1))

    def extend(self, tokens: List[int], start_tokens: int,
               take: bool = False,
               max_tokens: Optional[int] = None
               ) -> Tuple[int, Optional[np.ndarray],
                          Optional[np.ndarray]]:
        """Contiguous payload run BELOW ``start_tokens`` — the page-
        aligned depth a faster tier (the device radix cache) already
        covers. The walk to ``start_tokens`` may cross placeholder
        nodes; the returned run is payload pages only. With ``take``
        the matched pages leave this store (the caller moves them to
        the faster tier — every cached page lives in exactly one tier),
        their nodes staying as placeholders so deeper payloads remain
        reachable. ``max_tokens`` caps the run (a caller that allocated
        destination room from a peek must not receive more than it can
        place). Returns ``(extra_tokens, k, v)``."""
        ps = self.page_size
        n = (len(tokens) // ps) * ps
        start_tokens = (start_tokens // ps) * ps
        if max_tokens is not None:
            n = min(n, start_tokens + (max_tokens // ps) * ps)
        with self._lock:
            node = self.root
            i = 0
            now = time.monotonic()
            while i < start_tokens:
                child = node.children.get(tuple(tokens[i:i + ps]))
                if child is None:
                    self.metrics["misses"] += 1
                    return 0, None, None
                node = child
                i += ps
            ks, vs, run = [], [], []
            while i < n:
                child = node.children.get(tuple(tokens[i:i + ps]))
                if child is None or child.placeholder:
                    break
                child.last_used = now
                child.hits += 1
                ks.append(child.k)
                vs.append(child.v)
                run.append(child)
                i += ps
                node = child
            if not ks:
                self.metrics["misses"] += 1
                return 0, None, None
            self.metrics["hits"] += 1
            self.metrics["hit_tokens"] += i - start_tokens
        # Verify OUTSIDE the lock (match() rationale) and only then take:
        # a corrupt page must not be promoted to the device tier, and the
        # pages behind it must stay resident here for the next hit.
        good = self._verify_run(run, ks, vs)
        if good == 0:
            return 0, None, None
        if take:
            with self._lock:
                for nd in run[:good]:
                    if nd.placeholder:
                        continue   # a racing take already moved it
                    self.bytes -= nd.nbytes
                    self.metrics["pages"] -= 1
                    nd.k = nd.v = None
                    nd.crc = None
                    nd.nbytes = 0
                    nd.dirkey = ""   # caller re-registers as device tier
        # Stack outside the lock (match() rationale); the local ks/vs
        # refs keep taken arrays alive past the placeholder conversion.
        return (good * ps, np.stack(ks[:good], axis=1),
                np.stack(vs[:good], axis=1))

    def peek(self, tokens: List[int], start_tokens: int = 0) -> int:
        """Advisory payload-run depth below ``start_tokens`` — no LRU or
        hotness mutation (the admission TTFT predictor's read)."""
        ps = self.page_size
        n = (len(tokens) // ps) * ps
        start_tokens = (start_tokens // ps) * ps
        with self._lock:
            node = self.root
            i = 0
            while i < start_tokens:
                child = node.children.get(tuple(tokens[i:i + ps]))
                if child is None:
                    return 0
                node = child
                i += ps
            while i < n:
                child = node.children.get(tuple(tokens[i:i + ps]))
                if child is None or child.placeholder:
                    break
                i += ps
                node = child
        return i - start_tokens

    # ---- integrity ----

    def _verify_run(self, run: List[_Node], ks: List[np.ndarray],
                    vs: List[np.ndarray]) -> int:
        """Checksum-verify a matched payload run OUTSIDE the lock (the
        arrays are immutable once stored). Returns the count of leading
        good pages. The first corrupt page is dropped from the store and
        its directory claim invalidated — a rotted page must neither be
        served nor stay resident to poison the next lookup; the caller's
        hit simply ends one page earlier (graceful, never wrong)."""
        for j, nd in enumerate(run):
            crc = nd.crc
            if crc is None or payload_checksum(ks[j], vs[j]) == crc:
                continue
            REGISTRY.inc(obs_names.KVT_INTEGRITY_FAILURES_TOTAL,
                         surface="pool")
            dirkey = ""
            with self._lock:
                if not nd.placeholder:
                    self.bytes -= nd.nbytes
                    self.metrics["pages"] -= 1
                    self.metrics["evicted_pages"] += 1
                    nd.k = nd.v = None
                    nd.crc = None
                    nd.nbytes = 0
                    dirkey, nd.dirkey = nd.dirkey, ""
            if dirkey and self.directory is not None:
                self.directory.invalidate_keys(
                    [dirkey], reason="integrity",
                    backend=self.owner_backend)
            return j
        return len(run)

    # ---- insert ----

    def put(self, tokens: List[int], k: np.ndarray, v: np.ndarray,
            data_from_page: int = 0) -> int:
        """Store the page-aligned prefix of ``tokens``; ``k``/``v`` are
        ``[L, n_pages, page, KV, hd]`` covering the pages from
        ``data_from_page`` on (pages before it — held by a faster tier —
        become path-only placeholder nodes so later spills of deeper
        suffixes stay reachable). Existing pages are refreshed (LRU), not
        duplicated; a placeholder reached with payload is filled in.
        Returns pages newly stored."""
        ps = self.page_size
        n = min((len(tokens) // ps) * ps,
                (data_from_page + k.shape[1]) * ps if k is not None
                else data_from_page * ps)
        # Copy the page payloads BEFORE taking the lock (see match());
        # directory keys (the cross-process hash chain) likewise.
        from rbg_tpu.kvtransfer.chunks import prefix_keys
        dirkeys = prefix_keys(tokens[:n], ps)
        staged = []
        for pi in range(n // ps):
            if pi < data_from_page:
                staged.append((tuple(tokens[pi * ps:(pi + 1) * ps]),
                               None, None, "", None))
            else:
                ci = pi - data_from_page
                kp = np.ascontiguousarray(k[:, ci])
                vp = np.ascontiguousarray(v[:, ci])
                # Checksum minted at store time, outside the lock like
                # the payload copy — the match/extend verify leg reads it.
                staged.append((tuple(tokens[pi * ps:(pi + 1) * ps]),
                               kp, vp, dirkeys[pi],
                               payload_checksum(kp, vp)))
        new_pages = 0
        with self._lock:
            node = self.root
            now = time.monotonic()
            for key, kp, vp, dk, crc in staged:
                child = node.children.get(key)
                if child is not None:
                    child.last_used = now
                    if kp is not None and child.placeholder:
                        # A shallower page arrived after its deeper
                        # suffix (leaf-first radix eviction) — fill it.
                        child.k, child.v = kp, vp
                        child.nbytes = kp.nbytes + vp.nbytes
                        child.dirkey = dk
                        child.crc = crc
                        self.bytes += child.nbytes
                        new_pages += 1
                    node = child
                    continue
                # Children are keyed by the FULL page's tokens: prompts
                # sharing a first token but diverging inside a page coexist
                # as siblings instead of clobbering each other.
                child = _Node(key, node)
                child.last_used = now
                if kp is not None:
                    child.k, child.v = kp, vp
                    child.nbytes = kp.nbytes + vp.nbytes
                    child.dirkey = dk
                    child.crc = crc
                    self.bytes += child.nbytes
                    new_pages += 1
                node.children[key] = child
                node = child
            self.metrics["put_pages"] += new_pages
            self.metrics["pages"] += new_pages
            evicted_keys = self._evict_locked()
        if evicted_keys and self.directory is not None:
            # Outside the pool lock: a lookup racing this sees the prefix
            # a moment longer, but never AFTER invalidation completes —
            # the directory_consistent drill checks post-eviction lookups.
            self.directory.invalidate_keys(evicted_keys, reason="eviction",
                                           backend=self.owner_backend)
        return new_pages

    # ---- eviction ----

    def _evict_locked(self) -> List[str]:
        """Evict LRU leaves until under budget. Each pass walks the trie
        ONCE and evicts all current leaves in LRU order (a per-page
        full-trie scan would be O(pages²) under sustained pressure); a node
        whose children were all evicted becomes a leaf for the next pass.
        Returns the directory keys of evicted pages (the caller
        invalidates them outside this lock)."""
        evicted: List[str] = []
        while self.bytes > self.max_bytes:
            leaves = []
            stack = [self.root]
            while stack:
                node = stack.pop()
                if node is not self.root and not node.children:
                    leaves.append(node)
                stack.extend(node.children.values())
            if not leaves:
                return evicted
            # LRU-by-hotness WITH aging: coldest (fewest payload
            # matches) go first, recency breaks ties — and every
            # eviction pass halves the survivors' heat, so a prefix
            # that was hot months ago cannot hold the budget against
            # current traffic forever (hits only ever incremented
            # would otherwise turn the store into no-aging LFU).
            # Placeholder leaves (payload taken or never arrived) sort
            # first and cost nothing to drop. No pressure = no decay.
            for nd in leaves:
                nd.hits >>= 1
            leaves.sort(key=lambda nd: (nd.hits, nd.last_used))
            for leaf in leaves:
                if self.bytes <= self.max_bytes:
                    return evicted
                leaf.parent.children.pop(leaf.key, None)
                if not leaf.placeholder:
                    self.bytes -= leaf.nbytes
                    self.metrics["evicted_pages"] += 1
                    self.metrics["pages"] -= 1
                if leaf.dirkey:
                    evicted.append(leaf.dirkey)
        return evicted

    def stats(self) -> dict:
        with self._lock:
            return {**self.metrics, "bytes": self.bytes,
                    "max_bytes": self.max_bytes}


# ---- wire service ----


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        # TLS wraps PER CONNECTION on the worker thread, never on the
        # accept loop — a wrapped listener would run the handshake inside
        # serve_forever, letting one silent peer (port scanner, half-open
        # flow) freeze every prefill replica's pool access (same pattern
        # as the admin wire, runtime/admin.py).
        ctx = self.server.ssl_context
        self._tls_failed = False
        if ctx is not None:
            self.request.settimeout(10.0)  # bound the handshake
            try:
                self.request = ctx.wrap_socket(self.request, server_side=True)
            except OSError:  # ssl.SSLError / timeout / reset — drop peer
                self._tls_failed = True
                return
            self.request.settimeout(None)

    def handle(self):
        if self._tls_failed:
            return
        store: KVPoolStore = self.server.store
        while True:
            try:
                obj, k, v = recv_msg(self.request)
            except (ConnectionError, json.JSONDecodeError):
                return
            if obj is None:
                return
            try:
                self._dispatch(store, obj, k, v)
            except Exception as e:  # noqa: BLE001 — reply, don't die:
                # a malformed frame (bad shape/dtype, truncated payload)
                # must produce an error REPLY, not a dead handler thread
                # and an EOF on the client.
                try:
                    send_msg(self.request, {"error": f"{type(e).__name__}: {e}"})
                except OSError:
                    return

    def _dispatch(self, store, obj, k, v):
        op = obj.get("op")
        token = self.server.auth_token
        if token and op != "health":
            # Shared-token gate on every data op: an unauthenticated peer
            # must neither read KV (match leaks computed activations) nor
            # poison the store (put). Constant-time compare.
            if not token_ok(obj.get("token"), token):
                send_msg(self.request, {"error": "unauthorized"})
                return
        ps = obj.get("page_size")
        if (op in ("pool_match", "pool_put") and ps is not None
                and ps != store.page_size):
            # Page-size handshake: a mismatched client would interpret
            # the page arrays wrong (silently corrupt KV) — refuse.
            send_msg(self.request, {"error": (
                f"page_size mismatch: pool={store.page_size} "
                f"client={ps}")})
            return
        if op == "pool_match":
            matched, km, vm = store.match(obj["prompt"])
            if matched == 0:
                send_msg(self.request, {"matched": 0})
            else:
                kb, vb = km.tobytes(), vm.tobytes()
                # End-to-end: the checksum covers the stacked payload as
                # sent, so a peer fetch is verified at the RECEIVER —
                # corruption on this hop degrades to a miss, never KV.
                send_msg(self.request, {
                    "matched": matched,
                    "k_shape": list(km.shape), "v_shape": list(vm.shape),
                    "dtype": str(km.dtype),
                    "checksum": payload_checksum(kb, vb),
                }, kb, vb)
        elif op == "pool_put":
            ks = np.frombuffer(k, dtype=obj["dtype"]).reshape(obj["k_shape"])
            vs = np.frombuffer(v, dtype=obj["dtype"]).reshape(obj["v_shape"])
            stored = store.put(obj["prompt"], ks, vs)
            send_msg(self.request, {"stored_pages": stored})
        elif op in ("dir_register", "dir_lookup", "dir_invalidate",
                    "dir_stats"):
            d = store.directory
            if d is None:
                send_msg(self.request, {"error": "no directory configured"})
                return
            if op == "dir_register":
                n = d.register_keys(list(obj.get("keys") or ()),
                                    obj.get("backend") or "",
                                    slice_id=obj.get("slice_id") or "",
                                    tier=obj.get("tier") or "device")
                send_msg(self.request, {"registered": n})
            elif op == "dir_lookup":
                if "prompt" in obj:
                    # Key chain computed HERE with the pool's page size —
                    # routers hold no engine config.
                    from rbg_tpu.kvtransfer.chunks import prefix_keys
                    keys = prefix_keys(list(obj["prompt"]),
                                       store.page_size)
                else:
                    keys = list(obj.get("keys") or ())
                matched, detail = d.lookup_entries(keys)
                reply = {
                    "matched": matched,
                    "matched_tokens": matched * store.page_size,
                    "holders": [e["backend"] for e in detail]}
                if obj.get("detail"):
                    # Tier + hotness per holder — the router's tier-
                    # fetch-cost scoring input.
                    reply["detail"] = detail
                send_msg(self.request, reply)
            elif op == "dir_invalidate":
                reason = obj.get("reason") or "explicit"
                n = 0
                if obj.get("keys"):
                    # keys + backend = that replica's claims for those
                    # keys only (per-replica host-tier eviction must not
                    # wipe siblings' claims for a shared prefix hash).
                    n += d.invalidate_keys(list(obj["keys"]), reason,
                                           backend=obj.get("backend")
                                           or "")
                elif obj.get("backend"):
                    n += d.invalidate_backend(obj["backend"], reason)
                if obj.get("slice_id"):
                    n += d.invalidate_slice(obj["slice_id"], reason)
                send_msg(self.request, {"invalidated": n})
            else:
                send_msg(self.request, {"directory": d.stats(),
                                        "mode": "kvpool"})
        elif op == "pool_stats" or op == "metrics":
            stats = {"metrics": store.stats(), "mode": "kvpool"}
            if store.directory is not None:
                stats["directory"] = store.directory.stats()
            send_msg(self.request, stats)
        elif op == "health":
            send_msg(self.request, {"ok": True, "mode": "kvpool"})
        else:
            send_msg(self.request, {"error": f"unsupported op {op!r}"})


class KVPoolServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, store: KVPoolStore,
                 auth_token: Optional[str] = None,
                 ssl_context: Optional[ssl.SSLContext] = None):
        super().__init__(addr, _Handler)
        self.store = store
        self.auth_token = auth_token
        self.ssl_context = ssl_context


class KVPoolClient:
    """Prefill-side client. One short-lived connection per op (the ops are
    rare relative to decode steps: once per admitted prompt)."""

    def __init__(self, addr: str, timeout: float = 30.0,
                 page_size: Optional[int] = None,
                 token: Optional[str] = None,
                 ca_path: Optional[str] = None):
        host, port = addr.rsplit(":", 1)
        self.host = host
        self.addr = (host, int(port))
        self.timeout = timeout
        self.page_size = page_size   # engine's page size; server verifies
        self.token = (token if token is not None
                      else os.environ.get("RBG_DATA_TOKEN") or None)
        self._ssl = None
        if ca_path:
            from rbg_tpu.runtime.tlsutil import client_context
            self._ssl = client_context(ca_path)

    def _roundtrip(self, obj, k=None, v=None):
        if self.page_size is not None:
            obj["page_size"] = self.page_size
        if self.token:
            obj["token"] = self.token
        with socket.create_connection(self.addr, timeout=self.timeout) as raw:
            if self._ssl is not None:
                with self._ssl.wrap_socket(raw,
                                           server_hostname=self.host) as s:
                    send_msg(s, obj, k, v)
                    resp = recv_msg(s)
            else:
                send_msg(raw, obj, k, v)
                resp = recv_msg(raw)
        if resp[0] is None:
            # EOF without a reply (pool restarting / handler died):
            # RuntimeError keeps this inside the callers' degrade path.
            raise RuntimeError("kv pool closed the connection mid-request")
        return resp

    def match(self, prompt: List[int]):
        obj, k, v = self._roundtrip({"op": "pool_match", "prompt": list(prompt)})
        if obj.get("error"):
            raise RuntimeError(obj["error"])
        if obj["matched"] == 0:
            return 0, None, None
        cs = obj.get("checksum")
        if cs is not None \
                and payload_checksum(k or b"", v or b"") != int(cs):
            # Bytes rotted on the peer-fetch hop: a miss (the caller
            # recomputes — correct and cheap), never corrupt KV.
            REGISTRY.inc(obs_names.KVT_INTEGRITY_FAILURES_TOTAL,
                         surface="peer_fetch")
            return 0, None, None
        km = np.frombuffer(k, dtype=obj["dtype"]).reshape(obj["k_shape"])
        vm = np.frombuffer(v, dtype=obj["dtype"]).reshape(obj["v_shape"])
        return obj["matched"], km, vm

    def put(self, prompt: List[int], k: np.ndarray, v: np.ndarray) -> int:
        k = np.ascontiguousarray(k)
        v = np.ascontiguousarray(v)
        obj, _, _ = self._roundtrip({
            "op": "pool_put", "prompt": list(prompt),
            "k_shape": list(k.shape), "v_shape": list(v.shape),
            "dtype": str(k.dtype),
        }, k.tobytes(), v.tobytes())
        if obj.get("error"):
            raise RuntimeError(obj["error"])
        return obj["stored_pages"]

    def stats(self) -> dict:
        obj, _, _ = self._roundtrip({"op": "pool_stats"})
        return obj.get("metrics", {})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("rbg-tpu kv-pool server")
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--max-bytes", type=int, default=1 << 30)
    ap.add_argument("--auth-token",
                    default=os.environ.get("RBG_DATA_TOKEN", ""),
                    help="require this bearer token on every data op "
                         "(default: $RBG_DATA_TOKEN; empty = open wire)")
    ap.add_argument("--cert-dir", default="",
                    help="serve TLS with certs from this dir (bootstrapped "
                         "via runtime.tlsutil.ensure_certs, same CA "
                         "machinery as the admin wire)")
    args = ap.parse_args(argv)
    from rbg_tpu.kvtransfer.directory import PrefixDirectory
    store = KVPoolStore(args.page_size, max_bytes=args.max_bytes,
                        directory=PrefixDirectory(
                            page_size=args.page_size))
    ctx = None
    if args.cert_dir:
        from rbg_tpu.runtime.tlsutil import ensure_certs, server_context
        _ca, cert, key = ensure_certs(args.cert_dir)
        ctx = server_context(cert, key)
    srv = KVPoolServer(("0.0.0.0", args.port), store,
                       auth_token=args.auth_token or None, ssl_context=ctx)
    print(f"kv-pool serving on :{args.port}"
          f"{' [tls]' if ctx else ''}{' [auth]' if args.auth_token else ''}",
          flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
