"""Grammar-constrained decoding: JSON mode (structured output).

Reference context: structured output is the signature feature of the
reference's flagship engine (SGLang — the "structured generation
language"); vLLM ships it as guided/JSON mode. Here it is a byte-level
JSON pushdown automaton lifted to token masks:

* ``JsonGrammar`` — immutable-state automaton over BYTES. ``advance``
  returns the next state or None (byte illegal); ``is_complete`` says a
  full JSON value has been consumed (EOS becomes legal).
* ``TokenGrammar`` — lifts a byte grammar over a token→bytes table:
  ``mask(state)`` marks every token whose full byte sequence is legal
  from ``state`` (plus EOS iff complete); ``advance_token`` folds a
  token's bytes into the state.

Engine integration (engine.py), two paths:

* **Device-resident tables** — finite-state grammars (the NFA family
  below) additionally compile to a dense token-level product automaton
  (``compile_token_table``: ``next_state[S, V]`` + ``legal[S, V]``,
  BFS capped by a state budget), uploaded once per (grammar, vocab);
  constrained rows then decode INSIDE the fused multi-step scan with
  zero per-token host syncs, bit-identical to the mask path.
* **Host-synced masks** — the pushdown ``JsonGrammar``, budget-exceeded
  grammars, and speculative mode decode through the spec-style
  host-synced step. Masks for drafted positions are computed host-side
  ALONG THE DRAFT PATH — the mask at position i+1 assumes drafts 0..i
  were accepted, which holds exactly for every accepted prefix, so
  grammar constraints and speculative decoding compose without
  approximation (a draft token the grammar forbids truncates the draft).

Complexity note: ``mask`` walks a precompiled byte-path TRIE over the
vocabulary (xgrammar-style): the automaton advances once per trie NODE,
so tokens sharing a prefix share the walk and an illegal first byte
prunes its whole subtree — O(legal byte paths) per step instead of
O(total vocab bytes). Masks are additionally memoized per automaton
state (states recur heavily: a long string interior, number digits, the
AFTER-value gap all map to one state each), so steady-state decoding
costs a dict hit + memcpy. Exactness is preserved — the probe loop
survives as ``_mask_probe`` and tests assert trie == probe on every
state they visit.
"""

from __future__ import annotations

import dataclasses
import json
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

# ---- JSON byte automaton ----
#
# State = (mode, stack, aux) — plain tuples, hashable, never mutated.
#   mode: one of the _M_* constants below
#   stack: tuple of b'{' / b'[' container markers
#   aux: mode-specific scalar (literal progress, number sub-state, …)

_WS = frozenset(b" \t\n\r")
_DIGITS = frozenset(b"0123456789")
_HEX = frozenset(b"0123456789abcdefABCDEF")

# modes
_VALUE = 0          # expecting a value
_STRING = 1         # inside a string (aux: 0 normal, 1 after backslash,
                    #                  2-5 unicode escape digits remaining)
_KEYSTR = 2         # inside an object key string (same aux)
_AFTER = 3          # after a complete value (expect , } ] or EOS at top)
_OBJ_KEY = 4        # inside {, expecting key string or }
_OBJ_COLON = 5      # after key, expecting :
_OBJ_NEXTKEY = 6    # after comma in object, expecting key string
_NUM = 7            # inside a number (aux: sub-state)
_LIT = 8            # inside true/false/null (aux: (literal, idx))

# number sub-states (aux for _NUM)
_N_MINUS = 0        # consumed '-', need first digit
_N_ZERO = 1         # consumed leading 0 (no more int digits)
_N_INT = 2          # in integer digits
_N_DOT = 3          # consumed '.', need fraction digit
_N_FRAC = 4         # in fraction digits
_N_E = 5            # consumed e/E, need sign or digit
_N_ESIGN = 6        # consumed exponent sign, need digit
_N_EXP = 7          # in exponent digits

_NUM_COMPLETE = {_N_ZERO, _N_INT, _N_FRAC, _N_EXP}

State = Tuple[int, tuple, object]


class JsonGrammar:
    def initial(self) -> State:
        return (_VALUE, (), None)

    # -- helpers --

    @staticmethod
    def _close(stack: tuple) -> State:
        """A value just completed; what comes next."""
        return (_AFTER, stack, None)

    def _open_value(self, b: int, stack: tuple,
                    aux: object) -> Optional[State]:
        # aux == "af" marks "first slot of an array" — the only VALUE
        # position where a closing ] is legal ([] yes, [1,] no).
        if b in _WS:
            return (_VALUE, stack, aux)
        if b == 0x7B:                                   # {
            return (_OBJ_KEY, stack + (b"{",), None)
        if b == 0x5B:                                   # [
            return (_VALUE, stack + (b"[",), "af")
        if b == 0x22:                                   # "
            return (_STRING, stack, 0)
        if b == 0x2D:                                   # -
            return (_NUM, stack, _N_MINUS)
        if b == 0x30:                                   # 0
            return (_NUM, stack, _N_ZERO)
        if b in _DIGITS:
            return (_NUM, stack, _N_INT)
        for lit in (b"true", b"false", b"null"):
            if b == lit[0]:
                return (_LIT, stack, (lit, 1))
        if (b == 0x5D and aux == "af"
                and stack and stack[-1] == b"["):       # ] — empty array
            return self._close(stack[:-1])
        return None

    def _string_step(self, mode: int, b: int, stack: tuple,
                     aux: int) -> Optional[State]:
        if aux == 1:                                     # after backslash
            if b in b'"\\/bfnrt':
                return (mode, stack, 0)
            if b == 0x75:                                # u
                return (mode, stack, 2)
            return None
        if aux >= 2:                                     # unicode digits
            if b in _HEX:
                return (mode, stack, 0 if aux == 5 else aux + 1)
            return None
        if b == 0x22:                                    # closing quote
            if mode == _KEYSTR:
                return (_OBJ_COLON, stack, None)
            return self._close(stack)
        if b == 0x5C:                                    # backslash
            return (mode, stack, 1)
        if b < 0x20:                                     # raw control char
            return None
        return (mode, stack, 0)                          # any other byte

    def _num_step(self, b: int, stack: tuple, aux: int) -> Optional[State]:
        if aux == _N_MINUS:
            if b == 0x30:
                return (_NUM, stack, _N_ZERO)
            if b in _DIGITS:
                return (_NUM, stack, _N_INT)
            return None
        if aux in (_N_ZERO, _N_INT):
            if aux == _N_INT and b in _DIGITS:
                return (_NUM, stack, _N_INT)
            if b == 0x2E:                                # .
                return (_NUM, stack, _N_DOT)
            if b in (0x65, 0x45):                        # e E
                return (_NUM, stack, _N_E)
            return self._after_number(b, stack)
        if aux == _N_DOT:
            return (_NUM, stack, _N_FRAC) if b in _DIGITS else None
        if aux == _N_FRAC:
            if b in _DIGITS:
                return (_NUM, stack, _N_FRAC)
            if b in (0x65, 0x45):
                return (_NUM, stack, _N_E)
            return self._after_number(b, stack)
        if aux == _N_E:
            if b in (0x2B, 0x2D):                        # + -
                return (_NUM, stack, _N_ESIGN)
            return (_NUM, stack, _N_EXP) if b in _DIGITS else None
        if aux == _N_ESIGN:
            return (_NUM, stack, _N_EXP) if b in _DIGITS else None
        if aux == _N_EXP:
            if b in _DIGITS:
                return (_NUM, stack, _N_EXP)
            return self._after_number(b, stack)
        return None

    def _after_number(self, b: int, stack: tuple) -> Optional[State]:
        """A number ended implicitly — re-dispatch the byte in AFTER."""
        return self.advance(self._close(stack), b)

    # -- public --

    def advance(self, state: State, b: int) -> Optional[State]:
        mode, stack, aux = state
        if mode == _VALUE:
            return self._open_value(b, stack, aux)
        if mode in (_STRING, _KEYSTR):
            return self._string_step(mode, b, stack, aux)
        if mode == _NUM:
            return self._num_step(b, stack, aux)
        if mode == _LIT:
            lit, i = aux
            if b == lit[i]:
                if i + 1 == len(lit):
                    return self._close(stack)
                return (_LIT, stack, (lit, i + 1))
            return None
        if mode == _AFTER:
            if b in _WS:
                return (_AFTER, stack, None)
            if stack:
                top = stack[-1]
                if b == 0x2C:                            # ,
                    if top == b"{":
                        return (_OBJ_NEXTKEY, stack, None)
                    return (_VALUE, stack, None)
                if b == 0x7D and top == b"{":            # }
                    return self._close(stack[:-1])
                if b == 0x5D and top == b"[":            # ]
                    return self._close(stack[:-1])
            return None
        if mode in (_OBJ_KEY, _OBJ_NEXTKEY):
            if b in _WS:
                return (mode, stack, None)
            if b == 0x22:
                return (_KEYSTR, stack, 0)
            if b == 0x7D and mode == _OBJ_KEY:           # } — empty object
                return self._close(stack[:-1])
            return None
        if mode == _OBJ_COLON:
            if b in _WS:
                return (mode, stack, None)
            if b == 0x3A:                                # :
                return (_VALUE, stack, None)
            return None
        return None

    def is_complete(self, state: State) -> bool:
        mode, stack, aux = state
        if stack:
            return False
        if mode == _AFTER:
            return True
        if mode == _NUM:
            return aux in _NUM_COMPLETE
        return False


class NfaGrammar:
    """Byte-level Thompson-NFA grammar base: compiles a tuple AST
    (``("lit", byte)``, ``("class", frozenset)``, ``("cat", [...])``,
    ``("alt", [...])``, ``("rep", node, lo, hi|None)``) and exposes the
    same ``initial``/``advance``/``is_complete`` contract as JsonGrammar —
    state is a frozenset of node ids (hashable), so the ``TokenGrammar``
    trie walk and packed mask cache apply unchanged. Subclasses build the
    AST (the regex parser, the JSON-Schema compiler)."""

    _MAX_NODES = 10_000
    # '.', negated classes, and negated escapes complement within ASCII:
    # bytes 0x80-0xFF are UTF-8 continuation/lead fragments, and making a
    # lone one legal would force-sample undecodable output. Non-ASCII
    # characters still match as LITERALS (their full byte sequence).
    _ASCII = frozenset(range(0x80))

    def __init__(self, ast):
        self._trans: List[dict] = []      # node -> {byte: [targets]}
        self._eps: List[list] = []        # node -> [targets]
        start, end = self._compile(ast)
        self._accept = end
        self._start_closure = self._closure({start})
        # Precompute eps-closures per node for fast advance.
        self._node_closure = [self._closure({n})
                              for n in range(len(self._trans))]

    # -- NFA construction --

    def _node(self) -> int:
        if len(self._trans) >= self._MAX_NODES:
            raise ValueError("grammar: pattern/schema too large")
        self._trans.append({})
        self._eps.append([])
        return len(self._trans) - 1

    def _compile(self, ast):
        """Returns (start, end) node ids; fresh nodes per call so ``rep``
        expansion can instantiate the body repeatedly."""
        kind = ast[0]
        if kind == "lit":
            s, e = self._node(), self._node()
            self._trans[s].setdefault(ast[1], [])
            self._trans[s][ast[1]].append(e)
            return s, e
        if kind == "class":
            s, e = self._node(), self._node()
            for b in ast[1]:
                self._trans[s].setdefault(b, []).append(e)
            return s, e
        if kind == "cat":
            if not ast[1]:
                s = self._node()
                return s, s
            s, e = self._compile(ast[1][0])
            for item in ast[1][1:]:
                s2, e2 = self._compile(item)
                self._eps[e].append(s2)
                e = e2
            return s, e
        if kind == "alt":
            s, e = self._node(), self._node()
            for branch in ast[1]:
                bs, be = self._compile(branch)
                self._eps[s].append(bs)
                self._eps[be].append(e)
            return s, e
        if kind == "rep":
            _, body, lo, hi = ast
            s = self._node()
            cur = s
            for _ in range(lo):
                bs, be = self._compile(body)
                self._eps[cur].append(bs)
                cur = be
            if hi is None:                      # unbounded tail: loop
                bs, be = self._compile(body)
                self._eps[cur].append(bs)
                self._eps[be].append(bs)
                end = self._node()
                self._eps[cur].append(end)
                self._eps[be].append(end)
                return s, end
            end = self._node()
            self._eps[cur].append(end)
            for _ in range(hi - lo):            # optional copies
                bs, be = self._compile(body)
                self._eps[cur].append(bs)
                self._eps[be].append(end)
                cur = be
            return s, end
        raise AssertionError(kind)

    def _closure(self, nodes) -> frozenset:
        out = set(nodes)
        stack = list(nodes)
        while stack:
            n = stack.pop()
            for t in self._eps[n]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    # -- AST helpers shared by subclasses --

    @staticmethod
    def _lit_bytes(bs: bytes):
        if len(bs) == 1:
            return ("lit", bs[0])
        return ("cat", [("lit", b) for b in bs])

    # -- the JsonGrammar-compatible contract --

    def initial(self) -> frozenset:
        return self._start_closure

    def advance(self, state, b: int):
        nxt = set()
        for n in state:
            for t in self._trans[n].get(b, ()):
                nxt |= self._node_closure[t]
        return frozenset(nxt) if nxt else None

    def is_complete(self, state) -> bool:
        return self._accept in state


class RegexGrammar(NfaGrammar):
    """Byte-level regex automaton for constrained decoding (the ``regex``
    sampling param — vLLM guided_regex / sglang regex analog). Compiles a
    practical, ASCII-oriented subset to a Thompson NFA.

    Supported syntax: literal characters (non-ASCII literals match their
    UTF-8 bytes in sequence), ``.`` (any byte except newline), escapes
    ``\\d \\w \\s \\n \\t \\r`` and literal-escapes (``\\. \\[`` …),
    character classes ``[a-z0-9_]`` with ranges and ``^`` negation (ASCII
    members only), grouping ``()``, alternation ``|``, and quantifiers
    ``* + ?`` / ``{m} {m,} {m,n}``. Matching is ANCHORED at both ends —
    the whole generated output must match, the only sensible contract for
    generation. EOS becomes legal exactly at accepting states."""

    def __init__(self, pattern: str):
        self.pattern = pattern
        super().__init__(self.parse_ast(pattern))

    @classmethod
    def parse_ast(cls, pattern: str):
        """Parse a pattern to the shared AST without building an NFA —
        the JSON-Schema compiler embeds string ``pattern`` constraints."""
        self = object.__new__(cls)
        ast, i = self._parse_alt(pattern, 0)
        if i != len(pattern):
            raise ValueError(f"regex: unexpected {pattern[i]!r} at {i}")
        return ast

    # -- parsing (recursive descent to a tuple AST) --

    def _parse_alt(self, p: str, i: int):
        branches = []
        node, i = self._parse_cat(p, i)
        branches.append(node)
        while i < len(p) and p[i] == "|":
            node, i = self._parse_cat(p, i + 1)
            branches.append(node)
        return (branches[0] if len(branches) == 1
                else ("alt", branches)), i

    def _parse_cat(self, p: str, i: int):
        items = []
        while i < len(p) and p[i] not in "|)":
            atom, i = self._parse_atom(p, i)
            atom, i = self._parse_quant(p, i, atom)
            items.append(atom)
        if len(items) == 1:
            return items[0], i
        return ("cat", items), i

    def _parse_atom(self, p: str, i: int):
        c = p[i]
        if c == "(":
            node, i = self._parse_alt(p, i + 1)
            if i >= len(p) or p[i] != ")":
                raise ValueError("regex: unbalanced '('")
            return node, i + 1
        if c == "[":
            return self._parse_class(p, i + 1)
        if c == ".":
            return ("class", self._ASCII - {0x0A}), i + 1
        if c == "\\":
            if i + 1 >= len(p):
                raise ValueError("regex: dangling backslash")
            return self._escape(p[i + 1]), i + 2
        if c in ")|*+?{":
            raise ValueError(f"regex: unexpected {c!r} at {i}")
        return self._literal(c), i + 1

    @staticmethod
    def _literal(c: str):
        bs = c.encode("utf-8")
        if len(bs) == 1:
            return ("lit", bs[0])
        return ("cat", [("lit", b) for b in bs])

    _ESCAPE_CLASSES = {
        "d": frozenset(b"0123456789"),
        "w": frozenset(b"abcdefghijklmnopqrstuvwxyz"
                       b"ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
        "s": frozenset(b" \t\n\r\f\v"),
    }
    _ESCAPE_LITERALS = {"n": 0x0A, "t": 0x09, "r": 0x0D}

    def _escape(self, c: str):
        if c in self._ESCAPE_CLASSES:
            return ("class", self._ESCAPE_CLASSES[c])
        if c.isupper() and c.lower() in self._ESCAPE_CLASSES:
            # Negated escapes complement within ASCII: bytes >= 0x80 are
            # UTF-8 fragments — legalizing a lone continuation byte would
            # let the engine emit invalid UTF-8 (see _ASCII note).
            return ("class",
                    self._ASCII - self._ESCAPE_CLASSES[c.lower()])
        if c in self._ESCAPE_LITERALS:
            return ("lit", self._ESCAPE_LITERALS[c])
        if c.isalnum():
            # \b \B \A \Z \G and friends carry regex SEMANTICS we don't
            # implement — silently compiling them as literal letters
            # would force-emit wrong output. Admission error instead.
            raise ValueError(f"regex: unsupported escape \\{c}")
        if ord(c) < 128:
            return ("lit", ord(c))   # escaped punctuation: literal
        raise ValueError(f"regex: unsupported escape \\{c}")

    def _parse_class(self, p: str, i: int):
        negate = i < len(p) and p[i] == "^"
        if negate:
            i += 1
        members = set()
        first = True
        while i < len(p) and (p[i] != "]" or first):
            first = False
            if p[i] == "\\":
                if i + 1 >= len(p):
                    raise ValueError("regex: dangling backslash in class")
                e = self._escape(p[i + 1])
                members |= (e[1] if e[0] == "class" else {e[1]})
                i += 2
                continue
            c = p[i]
            if ord(c) > 127:
                raise ValueError("regex: non-ASCII in character class")
            if i + 2 < len(p) and p[i + 1] == "-" and p[i + 2] != "]":
                hi = p[i + 2]
                if ord(hi) > 127 or ord(hi) < ord(c):
                    raise ValueError(f"regex: bad range {c}-{hi}")
                members |= set(range(ord(c), ord(hi) + 1))
                i += 3
            else:
                members.add(ord(c))
                i += 1
        if i >= len(p):
            raise ValueError("regex: unterminated '['")
        if negate:
            members = self._ASCII - members
        return ("class", frozenset(members)), i + 1

    def _parse_quant(self, p: str, i: int, atom):
        if i >= len(p):
            return atom, i
        c = p[i]
        if c == "*":
            return ("rep", atom, 0, None), i + 1
        if c == "+":
            return ("rep", atom, 1, None), i + 1
        if c == "?":
            return ("rep", atom, 0, 1), i + 1
        if c == "{":
            j = p.find("}", i)
            if j < 0:
                raise ValueError("regex: unterminated '{'")
            body = p[i + 1:j]
            try:
                if "," not in body:
                    lo = hi = int(body)
                else:
                    lo_s, hi_s = body.split(",", 1)
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s else None
            except ValueError:
                raise ValueError(f"regex: bad quantifier {{{body}}}") from None
            if hi is not None and hi < lo:
                raise ValueError(f"regex: bad quantifier {{{body}}}")
            return ("rep", atom, lo, hi), j + 1
        return atom, i

class JsonSchemaGrammar(NfaGrammar):
    """JSON-Schema-constrained output (xgrammar / vLLM guided_json /
    OpenAI ``response_format: json_schema`` analog): compiles a schema
    subset into the shared byte NFA, so the output both parses as JSON
    and validates against the schema. Emission is COMPACT JSON (no
    whitespace) — every property in declaration order.

    Supported keywords: ``type`` object (``properties`` all emitted, in
    order), string (``minLength``/``maxLength``/``pattern`` — the
    pattern uses the RegexGrammar subset), number, integer, boolean,
    null; ``enum``/``const`` of JSON scalars; array (``items``,
    ``minItems``/``maxItems``); ``anyOf``/``oneOf`` as alternation;
    nesting to depth 16. Unsupported keywords (``$ref``, ``allOf``,
    ``patternProperties``, …) raise ValueError at admission."""

    _MAX_DEPTH = 16
    _UNSUPPORTED = ("$ref", "allOf", "not", "patternProperties",
                    "if", "then", "else", "dependentSchemas")
    # Constraint keywords this compiler actually ENFORCES. Anything else
    # that could change which documents validate is rejected at admission
    # (a keyword silently ignored would emit output the client's schema
    # rejects — the worst possible structured-output failure).
    _HANDLED = frozenset({
        "type", "properties", "items", "minItems", "maxItems",
        "minLength", "maxLength", "pattern", "enum", "const",
        "anyOf", "oneOf", "required", "additionalProperties"})
    # Annotation keywords with no validation semantics: safe to ignore.
    _ANNOTATIONS = frozenset({
        "title", "description", "default", "examples", "$schema", "$id",
        "$comment", "deprecated", "readOnly", "writeOnly"})

    def __init__(self, schema: dict):
        if not isinstance(schema, dict):
            raise ValueError("json_schema must be an object")
        self.schema = schema
        super().__init__(self._value_ast(schema, 0))

    # -- AST builders --

    def _value_ast(self, schema, depth: int):
        if not isinstance(schema, dict):
            # Bool/None subschemas and other malformed shapes must be
            # ADMISSION errors (ValueError), never handler TypeErrors.
            raise ValueError(
                f"json_schema: subschema must be an object, got "
                f"{type(schema).__name__}")
        if depth > self._MAX_DEPTH:
            raise ValueError("json_schema: nesting too deep")
        for kw in self._UNSUPPORTED:
            if kw in schema:
                raise ValueError(f"json_schema: unsupported keyword {kw!r}")
        for kw in schema:
            if kw not in self._HANDLED and kw not in self._ANNOTATIONS:
                raise ValueError(
                    f"json_schema: unrecognized constraint keyword {kw!r}"
                    " — this compiler enforces "
                    f"{sorted(self._HANDLED)} and refuses to silently "
                    "ignore anything else")
        self._check_required(schema)
        if "const" in schema:
            return self._scalar_lit(schema["const"])
        if "enum" in schema:
            vals = schema["enum"]
            if not isinstance(vals, list) or not vals:
                raise ValueError("json_schema: enum must be a non-empty list")
            return ("alt", [self._scalar_lit(v) for v in vals])
        if "anyOf" in schema or "oneOf" in schema:
            subs = schema.get("anyOf") if "anyOf" in schema \
                else schema.get("oneOf")
            if not isinstance(subs, list) or not subs:
                raise ValueError(
                    "json_schema: anyOf/oneOf must be a non-empty list")
            return ("alt", [self._value_ast(s, depth + 1) for s in subs])
        t = schema.get("type")
        if isinstance(t, list):
            return ("alt", [self._value_ast({**schema, "type": one},
                                            depth + 1) for one in t])
        if t == "object":
            return self._object_ast(schema, depth)
        if t == "array":
            return self._array_ast(schema, depth)
        if t == "string":
            return self._string_ast(schema)
        if t == "integer":
            return self._number_ast(integer=True)
        if t == "number":
            return self._number_ast(integer=False)
        if t == "boolean":
            return ("alt", [self._lit_bytes(b"true"),
                            self._lit_bytes(b"false")])
        if t == "null":
            return self._lit_bytes(b"null")
        raise ValueError(f"json_schema: unsupported type {t!r}")

    @staticmethod
    def _check_required(schema: dict) -> None:
        """``required`` and ``additionalProperties`` are accepted exactly
        when the compiler's emission already satisfies them by
        construction (every declared property emitted, nothing else);
        shapes that would need real enforcement raise."""
        if "required" in schema:
            req = schema["required"]
            props = schema.get("properties") or {}
            if not isinstance(req, list) or not isinstance(props, dict) \
                    or not set(req) <= set(props):
                raise ValueError(
                    "json_schema: required must list declared properties "
                    "(all properties are always emitted, so anything else "
                    "is unsatisfiable)")
        if schema.get("additionalProperties", False) is not False:
            raise ValueError(
                "json_schema: additionalProperties must be false/absent — "
                "emission is closed over the declared properties")

    @staticmethod
    def _scalar_lit(v):
        if isinstance(v, (dict, list)):
            raise ValueError("json_schema: enum/const members must be "
                             "scalars")
        return NfaGrammar._lit_bytes(
            json.dumps(v, ensure_ascii=False,
                       separators=(",", ":")).encode("utf-8"))

    def _object_ast(self, schema: dict, depth: int):
        props = schema.get("properties") or {}
        if not isinstance(props, dict):
            raise ValueError("json_schema: properties must be an object")
        if not props:
            return self._lit_bytes(b"{}")
        parts = [self._lit_bytes(b"{")]
        for i, (key, sub) in enumerate(props.items()):
            if i:
                parts.append(("lit", 0x2C))                   # ,
            parts.append(self._lit_bytes(
                json.dumps(key, ensure_ascii=False).encode("utf-8")))
            parts.append(("lit", 0x3A))                       # :
            parts.append(self._value_ast(sub, depth + 1))
        parts.append(self._lit_bytes(b"}"))
        return ("cat", parts)

    def _array_ast(self, schema: dict, depth: int):
        # Missing "items" means "any value members" in JSON Schema — a
        # shape this subset cannot emit. Defaulting to array-of-strings
        # here would CONSTRAIN output to something the client's schema
        # never asked for (the silent-divergence failure this compiler
        # exists to refuse): raise at admission like every other
        # unsupported shape. An explicit null/bool items raises in
        # _value_ast.
        if "items" not in schema:
            raise ValueError(
                "json_schema: array without 'items' (any-value members) "
                "is unsupported — declare an item schema")
        item = self._value_ast(schema["items"], depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        hi = int(hi) if hi is not None else None
        if lo < 0 or (hi is not None and hi < lo):
            raise ValueError("json_schema: bad minItems/maxItems")
        more = ("cat", [("lit", 0x2C), item])
        if lo == 0:
            nonempty = ("cat", [("lit", 0x5B), item,
                                ("rep", more, 0,
                                 None if hi is None else max(hi - 1, 0)),
                                ("lit", 0x5D)])
            if hi == 0:
                return self._lit_bytes(b"[]")
            return ("alt", [self._lit_bytes(b"[]"), nonempty])
        return ("cat", [("lit", 0x5B), item,
                        ("rep", more, lo - 1,
                         None if hi is None else hi - 1),
                        ("lit", 0x5D)])

    def _string_ast(self, schema: dict):
        if "pattern" in schema:
            # The pattern constrains the string CONTENT (anchored); the
            # compiler wraps it in quotes. Patterns that could match a
            # raw '"' or '\\' are the caller's foot-gun (same contract
            # as xgrammar).
            body = RegexGrammar.parse_ast(str(schema["pattern"]))
            return ("cat", [("lit", 0x22), body, ("lit", 0x22)])
        lo = int(schema.get("minLength", 0))
        hi = schema.get("maxLength")
        hi = int(hi) if hi is not None else None
        if lo < 0 or (hi is not None and hi < lo):
            raise ValueError("json_schema: bad minLength/maxLength")
        return ("cat", [("lit", 0x22),
                        ("rep", self._string_char(), lo, hi),
                        ("lit", 0x22)])

    @classmethod
    def _string_char(cls):
        """One JSON-string character: printable ASCII (minus quote and
        backslash), a JSON escape, or a STRICT multi-byte UTF-8 sequence
        (no overlongs, no surrogates — a mask must never force-sample
        bytes that cannot decode)."""
        ascii_ok = ("class", frozenset(range(0x20, 0x7F)) - {0x22, 0x5C})
        esc = ("cat", [("lit", 0x5C),
                       ("class", frozenset(b'"\\/bfnrt'))])
        uesc = ("cat", [("lit", 0x5C), ("lit", 0x75)]
               + [("class", frozenset(b"0123456789abcdefABCDEF"))] * 4)
        cont = ("class", frozenset(range(0x80, 0xC0)))
        two = ("cat", [("class", frozenset(range(0xC2, 0xE0))), cont])
        three = ("alt", [
            ("cat", [("lit", 0xE0),
                     ("class", frozenset(range(0xA0, 0xC0))), cont]),
            ("cat", [("class", frozenset(range(0xE1, 0xED))
                      | {0xEE, 0xEF}), cont, cont]),
            ("cat", [("lit", 0xED),
                     ("class", frozenset(range(0x80, 0xA0))), cont]),
        ])
        four = ("alt", [
            ("cat", [("lit", 0xF0),
                     ("class", frozenset(range(0x90, 0xC0))), cont, cont]),
            ("cat", [("class", frozenset(range(0xF1, 0xF4))),
                     cont, cont, cont]),
            ("cat", [("lit", 0xF4),
                     ("class", frozenset(range(0x80, 0x90))), cont, cont]),
        ])
        return ("alt", [ascii_ok, esc, uesc, two, three, four])

    @classmethod
    def _number_ast(cls, integer: bool):
        digit = ("class", frozenset(b"0123456789"))
        intpart = ("alt", [("lit", 0x30),
                           ("cat", [("class", frozenset(b"123456789")),
                                    ("rep", digit, 0, None)])])
        parts = [("rep", ("lit", 0x2D), 0, 1), intpart]
        if not integer:
            parts.append(("rep", ("cat", [("lit", 0x2E),
                                          ("rep", digit, 1, None)]), 0, 1))
            parts.append(("rep", ("cat", [
                ("class", frozenset(b"eE")),
                ("rep", ("class", frozenset(b"+-")), 0, 1),
                ("rep", digit, 1, None)]), 0, 1))
        return ("cat", parts)


class TokenTrie:
    """Byte-path trie over a token→bytes table, compiled once per
    tokenizer (the xgrammar move). Nodes are parallel lists:
    ``children[n]`` maps byte→child node, ``tokens[n]`` lists the token
    ids whose byte string ends at node n."""

    __slots__ = ("children", "tokens", "total_bytes")

    def __init__(self, token_bytes: List[Optional[bytes]]):
        self.children: List[dict] = [{}]
        self.tokens: List[list] = [[]]
        self.total_bytes = 0
        for tid, bs in enumerate(token_bytes):
            if not bs:
                continue
            self.total_bytes += len(bs)
            n = 0
            for b in bs:
                nxt = self.children[n].get(b)
                if nxt is None:
                    nxt = len(self.children)
                    self.children[n][b] = nxt
                    self.children.append({})
                    self.tokens.append([])
                n = nxt
            self.tokens[n].append(tid)


class TokenGrammar:
    """Lift a byte grammar over a token→bytes table.

    ``token_bytes[i]`` is the byte string token i appends, or None for
    tokens that must never appear inside constrained output (specials).
    ``eos_id`` is allowed exactly when the value is complete."""

    # Steady-state decoding revisits a few dozen states (string interior,
    # number digits, AFTER-gap, one per stack depth); masks are cached
    # bit-PACKED (V/8 bytes each) so even a full cache at a 100k vocab is
    # ~3 MB, not ~25 MB of bool arrays.
    MASK_CACHE_SIZE = 256

    def __init__(self, grammar, token_bytes: List[Optional[bytes]],
                 eos_id: Optional[int], trie: Optional[TokenTrie] = None):
        self.grammar = grammar
        self.token_bytes = token_bytes
        self.eos_id = eos_id
        self.V = len(token_bytes)
        # The trie depends only on the TOKENIZER — callers juggling many
        # grammars over one vocab (per-pattern regex cache) pass the one
        # shared instance instead of rebuilding O(vocab bytes) each time.
        self.trie = trie if trie is not None else TokenTrie(token_bytes)
        self._mask_cache: "OrderedDict[State, np.ndarray]" = OrderedDict()
        self.stats = {"mask_calls": 0, "mask_cache_hits": 0,
                      "advance_calls": 0}

    def initial(self) -> State:
        return self.grammar.initial()

    def advance_token(self, state: State, tok: int) -> Optional[State]:
        if tok == self.eos_id:
            return state if self.grammar.is_complete(state) else None
        bs = self.token_bytes[tok] if 0 <= tok < self.V else None
        if bs is None:
            return None
        for b in bs:
            state = self.grammar.advance(state, b)
            if state is None:
                return None
        return state

    def mask(self, state: State) -> np.ndarray:
        """[V] bool — tokens legal from ``state`` (EOS iff complete).
        Trie-walked and per-state memoized; callers own the returned
        array (a copy — masks are row-assigned into batch buffers)."""
        self.stats["mask_calls"] += 1
        cached = self._mask_cache.get(state)
        if cached is not None:
            self.stats["mask_cache_hits"] += 1
            self._mask_cache.move_to_end(state)
            return np.unpackbits(cached, count=self.V).astype(bool)
        out = np.zeros(self.V, bool)
        for toks, _ns in self._trie_walk(state):
            out[toks] = True
        if self.eos_id is not None and self.eos_id < self.V:
            out[self.eos_id] = self.grammar.is_complete(state)
        self._mask_cache[state] = np.packbits(out)
        if len(self._mask_cache) > self.MASK_CACHE_SIZE:
            self._mask_cache.popitem(last=False)
        return out

    def _trie_walk(self, state: State) -> List[Tuple[list, State]]:
        """(token ids, byte-grammar state) per trie node whose byte path
        is legal from ``state`` and ends at least one token. The SINGLE
        source of the legality walk: ``mask`` (which discards the states)
        and ``token_transitions`` (which keeps them) both consume it, so
        the table path's bit-identical-to-mask contract can't drift."""
        out: List[Tuple[list, State]] = []
        children = self.trie.children
        tokens = self.trie.tokens
        adv = self.grammar.advance
        n_adv = 0
        stack = [(0, state)]
        while stack:
            node, st = stack.pop()
            for b, child in children[node].items():
                n_adv += 1
                ns = adv(st, b)
                if ns is None:
                    continue
                toks = tokens[child]
                if toks:
                    out.append((toks, ns))
                if children[child]:
                    stack.append((child, ns))
        self.stats["advance_calls"] += n_adv
        return out

    def token_transitions(self, state: State) -> List[Tuple[int, State]]:
        """(token id, byte-grammar state after the token) for every
        non-special token legal from ``state``. EOS is NOT included (its
        transition is identity-on-complete; see ``advance_token``). The
        legal-token set is exactly ``mask(state)`` minus EOS — same walk,
        same trie (``_trie_walk``)."""
        return [(tid, ns) for toks, ns in self._trie_walk(state)
                for tid in toks]

    def _mask_probe(self, state: State) -> np.ndarray:
        """Reference implementation: probe every token's bytes from
        ``state``. O(total vocab bytes) — kept for exactness tests."""
        out = np.zeros(self.V, bool)
        adv = self.grammar.advance
        for i, bs in enumerate(self.token_bytes):
            if not bs:
                continue
            s = state
            ok = True
            for b in bs:
                s = adv(s, b)
                if s is None:
                    ok = False
                    break
            out[i] = ok
        if self.eos_id is not None and self.eos_id < self.V:
            out[self.eos_id] = self.grammar.is_complete(state)
        return out


@dataclasses.dataclass
class GrammarTable:
    """Token-level product automaton of (byte grammar × vocab), dense —
    the xgrammar-style device-resident form of a finite-state grammar.

    ``next_state[s, v]`` is the state after sampling token ``v`` in state
    ``s`` (−1 = illegal); ``legal[s, v]`` marks the tokens the grammar
    allows (EOS legal exactly at accepting states, where its transition is
    the identity — the engine finishes the row host-side, matching
    ``TokenGrammar.advance_token``'s keep-state-on-EOS contract). Row
    ``legal[s]`` equals the host path's ``mask(state)`` padded to the
    model vocab bit-for-bit: both come from the same trie walk, which is
    what makes fused table decode provably emit the host-synced stream.

    ``state_ids`` maps byte-grammar states to rows. It covers every state
    reachable from ``initial`` by WHOLE-token advances — the only states
    engine bookkeeping can ever hold (prefill, decode, PD injection, and
    preemption resume all advance token-at-a-time from initial)."""

    next_state: np.ndarray            # [S, V] int32, -1 = illegal
    legal: np.ndarray                 # [S, V] bool
    state_ids: Dict[State, int]       # byte-grammar state -> row
    initial_id: int = 0

    @property
    def num_states(self) -> int:
        return self.next_state.shape[0]

    @property
    def nbytes(self) -> int:
        return self.next_state.nbytes + self.legal.nbytes


def compile_token_table(tg: TokenGrammar, state_budget: int,
                        vocab_size: Optional[int] = None
                        ) -> Optional[GrammarTable]:
    """BFS the token-level automaton of ``tg`` into a ``GrammarTable``.

    Returns None when more than ``state_budget`` states are reachable —
    the caller keeps the host-synced mask path for that grammar. Intended
    for finite-state grammars (``NfaGrammar`` subclasses); a pushdown
    grammar (``JsonGrammar``) has unbounded reachable states and would
    simply exhaust the budget, so callers should gate on the grammar type
    and never pay the doomed BFS.

    ``vocab_size`` pads columns to the MODEL vocab (ids beyond the
    tokenizer's table are never legal — same contract as the engine's
    host-side ``_gmask`` padding). Memory: S × V × 5 bytes host-side
    (int32 + bool), uploaded once per (grammar, vocab) by the engine."""
    V = vocab_size if vocab_size is not None else tg.V
    g = tg.grammar
    init = tg.initial()
    states: List[State] = [init]
    ids: Dict[State, int] = {init: 0}
    rows_next: List[np.ndarray] = []
    rows_legal: List[np.ndarray] = []
    i = 0
    while i < len(states):
        st = states[i]
        nxt = np.full(V, -1, np.int32)
        legal = np.zeros(V, bool)
        for tok, ns in tg.token_transitions(st):
            if tok >= V:
                continue              # beyond the model vocab: never legal
            sid = ids.get(ns)
            if sid is None:
                if len(states) >= state_budget:
                    return None       # budget exceeded → host-synced path
                sid = len(states)
                ids[ns] = sid
                states.append(ns)
            nxt[tok] = sid
            legal[tok] = True
        if (tg.eos_id is not None and tg.eos_id < V
                and g.is_complete(st)):
            legal[tg.eos_id] = True
            nxt[tg.eos_id] = i        # EOS keeps the state; host finishes
        rows_next.append(nxt)
        rows_legal.append(legal)
        i += 1
    return GrammarTable(next_state=np.stack(rows_next),
                        legal=np.stack(rows_legal), state_ids=ids)


def token_bytes_for(tokenizer) -> List[Optional[bytes]]:
    """Build the token→bytes table for a tokenizer. The byte tokenizer
    maps id i (< 256) to byte i DIRECTLY — decode() would turn a lone
    UTF-8 continuation byte into U+FFFD and corrupt the table. Other
    tokenizers fall back to per-token decode (adequate for grammar
    probing; specials map to None)."""
    from rbg_tpu.engine.tokenizer import ByteTokenizer

    vocab = tokenizer.vocab_size
    specials = {getattr(tokenizer, a, None)
                for a in ("bos_id", "eos_id", "pad_id")}
    table: List[Optional[bytes]] = []
    if isinstance(tokenizer, ByteTokenizer):
        for i in range(vocab):
            table.append(bytes([i]) if i < 256 and i not in specials
                         else None)
        return table
    for i in range(vocab):
        if i in specials:
            table.append(None)
            continue
        try:
            s = tokenizer.decode([i])
        except Exception:   # noqa: BLE001 — unknown id quirks → unusable
            table.append(None)
            continue
        table.append(s.encode("utf-8", errors="ignore") or None)
    return table
