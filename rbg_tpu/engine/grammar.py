"""Grammar-constrained decoding: JSON mode (structured output).

Reference context: structured output is the signature feature of the
reference's flagship engine (SGLang — the "structured generation
language"); vLLM ships it as guided/JSON mode. Here it is a byte-level
JSON pushdown automaton lifted to token masks:

* ``JsonGrammar`` — immutable-state automaton over BYTES. ``advance``
  returns the next state or None (byte illegal); ``is_complete`` says a
  full JSON value has been consumed (EOS becomes legal).
* ``TokenGrammar`` — lifts a byte grammar over a token→bytes table:
  ``mask(state)`` marks every token whose full byte sequence is legal
  from ``state`` (plus EOS iff complete); ``advance_token`` folds a
  token's bytes into the state.

Engine integration (engine.py): constrained rows decode through the
spec-style host-synced step. Masks for drafted positions are computed
host-side ALONG THE DRAFT PATH — the mask at position i+1 assumes drafts
0..i were accepted, which holds exactly for every accepted prefix, so
grammar constraints and speculative decoding compose without
approximation (a draft token the grammar forbids truncates the draft).

Complexity note: ``mask`` walks a precompiled byte-path TRIE over the
vocabulary (xgrammar-style): the automaton advances once per trie NODE,
so tokens sharing a prefix share the walk and an illegal first byte
prunes its whole subtree — O(legal byte paths) per step instead of
O(total vocab bytes). Masks are additionally memoized per automaton
state (states recur heavily: a long string interior, number digits, the
AFTER-value gap all map to one state each), so steady-state decoding
costs a dict hit + memcpy. Exactness is preserved — the probe loop
survives as ``_mask_probe`` and tests assert trie == probe on every
state they visit.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

# ---- JSON byte automaton ----
#
# State = (mode, stack, aux) — plain tuples, hashable, never mutated.
#   mode: one of the _M_* constants below
#   stack: tuple of b'{' / b'[' container markers
#   aux: mode-specific scalar (literal progress, number sub-state, …)

_WS = frozenset(b" \t\n\r")
_DIGITS = frozenset(b"0123456789")
_HEX = frozenset(b"0123456789abcdefABCDEF")

# modes
_VALUE = 0          # expecting a value
_STRING = 1         # inside a string (aux: 0 normal, 1 after backslash,
                    #                  2-5 unicode escape digits remaining)
_KEYSTR = 2         # inside an object key string (same aux)
_AFTER = 3          # after a complete value (expect , } ] or EOS at top)
_OBJ_KEY = 4        # inside {, expecting key string or }
_OBJ_COLON = 5      # after key, expecting :
_OBJ_NEXTKEY = 6    # after comma in object, expecting key string
_NUM = 7            # inside a number (aux: sub-state)
_LIT = 8            # inside true/false/null (aux: (literal, idx))

# number sub-states (aux for _NUM)
_N_MINUS = 0        # consumed '-', need first digit
_N_ZERO = 1         # consumed leading 0 (no more int digits)
_N_INT = 2          # in integer digits
_N_DOT = 3          # consumed '.', need fraction digit
_N_FRAC = 4         # in fraction digits
_N_E = 5            # consumed e/E, need sign or digit
_N_ESIGN = 6        # consumed exponent sign, need digit
_N_EXP = 7          # in exponent digits

_NUM_COMPLETE = {_N_ZERO, _N_INT, _N_FRAC, _N_EXP}

State = Tuple[int, tuple, object]


class JsonGrammar:
    def initial(self) -> State:
        return (_VALUE, (), None)

    # -- helpers --

    @staticmethod
    def _close(stack: tuple) -> State:
        """A value just completed; what comes next."""
        return (_AFTER, stack, None)

    def _open_value(self, b: int, stack: tuple,
                    aux: object) -> Optional[State]:
        # aux == "af" marks "first slot of an array" — the only VALUE
        # position where a closing ] is legal ([] yes, [1,] no).
        if b in _WS:
            return (_VALUE, stack, aux)
        if b == 0x7B:                                   # {
            return (_OBJ_KEY, stack + (b"{",), None)
        if b == 0x5B:                                   # [
            return (_VALUE, stack + (b"[",), "af")
        if b == 0x22:                                   # "
            return (_STRING, stack, 0)
        if b == 0x2D:                                   # -
            return (_NUM, stack, _N_MINUS)
        if b == 0x30:                                   # 0
            return (_NUM, stack, _N_ZERO)
        if b in _DIGITS:
            return (_NUM, stack, _N_INT)
        for lit in (b"true", b"false", b"null"):
            if b == lit[0]:
                return (_LIT, stack, (lit, 1))
        if (b == 0x5D and aux == "af"
                and stack and stack[-1] == b"["):       # ] — empty array
            return self._close(stack[:-1])
        return None

    def _string_step(self, mode: int, b: int, stack: tuple,
                     aux: int) -> Optional[State]:
        if aux == 1:                                     # after backslash
            if b in b'"\\/bfnrt':
                return (mode, stack, 0)
            if b == 0x75:                                # u
                return (mode, stack, 2)
            return None
        if aux >= 2:                                     # unicode digits
            if b in _HEX:
                return (mode, stack, 0 if aux == 5 else aux + 1)
            return None
        if b == 0x22:                                    # closing quote
            if mode == _KEYSTR:
                return (_OBJ_COLON, stack, None)
            return self._close(stack)
        if b == 0x5C:                                    # backslash
            return (mode, stack, 1)
        if b < 0x20:                                     # raw control char
            return None
        return (mode, stack, 0)                          # any other byte

    def _num_step(self, b: int, stack: tuple, aux: int) -> Optional[State]:
        if aux == _N_MINUS:
            if b == 0x30:
                return (_NUM, stack, _N_ZERO)
            if b in _DIGITS:
                return (_NUM, stack, _N_INT)
            return None
        if aux in (_N_ZERO, _N_INT):
            if aux == _N_INT and b in _DIGITS:
                return (_NUM, stack, _N_INT)
            if b == 0x2E:                                # .
                return (_NUM, stack, _N_DOT)
            if b in (0x65, 0x45):                        # e E
                return (_NUM, stack, _N_E)
            return self._after_number(b, stack)
        if aux == _N_DOT:
            return (_NUM, stack, _N_FRAC) if b in _DIGITS else None
        if aux == _N_FRAC:
            if b in _DIGITS:
                return (_NUM, stack, _N_FRAC)
            if b in (0x65, 0x45):
                return (_NUM, stack, _N_E)
            return self._after_number(b, stack)
        if aux == _N_E:
            if b in (0x2B, 0x2D):                        # + -
                return (_NUM, stack, _N_ESIGN)
            return (_NUM, stack, _N_EXP) if b in _DIGITS else None
        if aux == _N_ESIGN:
            return (_NUM, stack, _N_EXP) if b in _DIGITS else None
        if aux == _N_EXP:
            if b in _DIGITS:
                return (_NUM, stack, _N_EXP)
            return self._after_number(b, stack)
        return None

    def _after_number(self, b: int, stack: tuple) -> Optional[State]:
        """A number ended implicitly — re-dispatch the byte in AFTER."""
        return self.advance(self._close(stack), b)

    # -- public --

    def advance(self, state: State, b: int) -> Optional[State]:
        mode, stack, aux = state
        if mode == _VALUE:
            return self._open_value(b, stack, aux)
        if mode in (_STRING, _KEYSTR):
            return self._string_step(mode, b, stack, aux)
        if mode == _NUM:
            return self._num_step(b, stack, aux)
        if mode == _LIT:
            lit, i = aux
            if b == lit[i]:
                if i + 1 == len(lit):
                    return self._close(stack)
                return (_LIT, stack, (lit, i + 1))
            return None
        if mode == _AFTER:
            if b in _WS:
                return (_AFTER, stack, None)
            if stack:
                top = stack[-1]
                if b == 0x2C:                            # ,
                    if top == b"{":
                        return (_OBJ_NEXTKEY, stack, None)
                    return (_VALUE, stack, None)
                if b == 0x7D and top == b"{":            # }
                    return self._close(stack[:-1])
                if b == 0x5D and top == b"[":            # ]
                    return self._close(stack[:-1])
            return None
        if mode in (_OBJ_KEY, _OBJ_NEXTKEY):
            if b in _WS:
                return (mode, stack, None)
            if b == 0x22:
                return (_KEYSTR, stack, 0)
            if b == 0x7D and mode == _OBJ_KEY:           # } — empty object
                return self._close(stack[:-1])
            return None
        if mode == _OBJ_COLON:
            if b in _WS:
                return (mode, stack, None)
            if b == 0x3A:                                # :
                return (_VALUE, stack, None)
            return None
        return None

    def is_complete(self, state: State) -> bool:
        mode, stack, aux = state
        if stack:
            return False
        if mode == _AFTER:
            return True
        if mode == _NUM:
            return aux in _NUM_COMPLETE
        return False


class TokenTrie:
    """Byte-path trie over a token→bytes table, compiled once per
    tokenizer (the xgrammar move). Nodes are parallel lists:
    ``children[n]`` maps byte→child node, ``tokens[n]`` lists the token
    ids whose byte string ends at node n."""

    __slots__ = ("children", "tokens", "total_bytes")

    def __init__(self, token_bytes: List[Optional[bytes]]):
        self.children: List[dict] = [{}]
        self.tokens: List[list] = [[]]
        self.total_bytes = 0
        for tid, bs in enumerate(token_bytes):
            if not bs:
                continue
            self.total_bytes += len(bs)
            n = 0
            for b in bs:
                nxt = self.children[n].get(b)
                if nxt is None:
                    nxt = len(self.children)
                    self.children[n][b] = nxt
                    self.children.append({})
                    self.tokens.append([])
                n = nxt
            self.tokens[n].append(tid)


class TokenGrammar:
    """Lift a byte grammar over a token→bytes table.

    ``token_bytes[i]`` is the byte string token i appends, or None for
    tokens that must never appear inside constrained output (specials).
    ``eos_id`` is allowed exactly when the value is complete."""

    # Steady-state decoding revisits a few dozen states (string interior,
    # number digits, AFTER-gap, one per stack depth); masks are cached
    # bit-PACKED (V/8 bytes each) so even a full cache at a 100k vocab is
    # ~3 MB, not ~25 MB of bool arrays.
    MASK_CACHE_SIZE = 256

    def __init__(self, grammar: JsonGrammar, token_bytes: List[Optional[bytes]],
                 eos_id: Optional[int]):
        self.grammar = grammar
        self.token_bytes = token_bytes
        self.eos_id = eos_id
        self.V = len(token_bytes)
        self.trie = TokenTrie(token_bytes)
        self._mask_cache: "OrderedDict[State, np.ndarray]" = OrderedDict()
        self.stats = {"mask_calls": 0, "mask_cache_hits": 0,
                      "advance_calls": 0}

    def initial(self) -> State:
        return self.grammar.initial()

    def advance_token(self, state: State, tok: int) -> Optional[State]:
        if tok == self.eos_id:
            return state if self.grammar.is_complete(state) else None
        bs = self.token_bytes[tok] if 0 <= tok < self.V else None
        if bs is None:
            return None
        for b in bs:
            state = self.grammar.advance(state, b)
            if state is None:
                return None
        return state

    def mask(self, state: State) -> np.ndarray:
        """[V] bool — tokens legal from ``state`` (EOS iff complete).
        Trie-walked and per-state memoized; callers own the returned
        array (a copy — masks are row-assigned into batch buffers)."""
        self.stats["mask_calls"] += 1
        cached = self._mask_cache.get(state)
        if cached is not None:
            self.stats["mask_cache_hits"] += 1
            self._mask_cache.move_to_end(state)
            return np.unpackbits(cached, count=self.V).astype(bool)
        out = np.zeros(self.V, bool)
        children = self.trie.children
        tokens = self.trie.tokens
        adv = self.grammar.advance
        n_adv = 0
        stack = [(0, state)]
        while stack:
            node, st = stack.pop()
            for b, child in children[node].items():
                n_adv += 1
                ns = adv(st, b)
                if ns is None:
                    continue
                toks = tokens[child]
                if toks:
                    out[toks] = True
                if children[child]:
                    stack.append((child, ns))
        self.stats["advance_calls"] += n_adv
        if self.eos_id is not None and self.eos_id < self.V:
            out[self.eos_id] = self.grammar.is_complete(state)
        self._mask_cache[state] = np.packbits(out)
        if len(self._mask_cache) > self.MASK_CACHE_SIZE:
            self._mask_cache.popitem(last=False)
        return out

    def _mask_probe(self, state: State) -> np.ndarray:
        """Reference implementation: probe every token's bytes from
        ``state``. O(total vocab bytes) — kept for exactness tests."""
        out = np.zeros(self.V, bool)
        adv = self.grammar.advance
        for i, bs in enumerate(self.token_bytes):
            if not bs:
                continue
            s = state
            ok = True
            for b in bs:
                s = adv(s, b)
                if s is None:
                    ok = False
                    break
            out[i] = ok
        if self.eos_id is not None and self.eos_id < self.V:
            out[self.eos_id] = self.grammar.is_complete(state)
        return out


def token_bytes_for(tokenizer) -> List[Optional[bytes]]:
    """Build the token→bytes table for a tokenizer. The byte tokenizer
    maps id i (< 256) to byte i DIRECTLY — decode() would turn a lone
    UTF-8 continuation byte into U+FFFD and corrupt the table. Other
    tokenizers fall back to per-token decode (adequate for grammar
    probing; specials map to None)."""
    from rbg_tpu.engine.tokenizer import ByteTokenizer

    vocab = tokenizer.vocab_size
    specials = {getattr(tokenizer, a, None)
                for a in ("bos_id", "eos_id", "pad_id")}
    table: List[Optional[bytes]] = []
    if isinstance(tokenizer, ByteTokenizer):
        for i in range(vocab):
            table.append(bytes([i]) if i < 256 and i not in specials
                         else None)
        return table
    for i in range(vocab):
        if i in specials:
            table.append(None)
            continue
        try:
            s = tokenizer.decode([i])
        except Exception:   # noqa: BLE001 — unknown id quirks → unusable
            table.append(None)
            continue
        table.append(s.encode("utf-8", errors="ignore") or None)
    return table
