"""Router process — the front role of a PD-disagg group.

Reference analog: the sglang-router role in ``examples/inference/
pd-disagg-*.yaml`` (router → prefill → decode with startup dependencies).
Discovers its backends from the address registry the executor maintains
(or static ``--backends``):

* registry entries carry the role name, so PD mode switches on automatically
  when ``prefill`` and ``decode`` roles exist: prefill op → KV bundle over
  the wire → decode_bundle op on a decode peer (Mooncake-style transfer).
* otherwise round-robins ``generate`` over unified workers.
"""

from __future__ import annotations

import argparse
import json
import os
import socketserver
import sys
import time
from typing import Dict, List, Optional

from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg


class Registry:
    """Pod address registry: {fqdn: {addr, role, group}} JSON file, written
    atomically by the executor; re-read (mtime-cached) per lookup."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._cache: Dict[str, dict] = {}
        self._mtime = 0.0

    def entries(self) -> Dict[str, dict]:
        if not self.path or not os.path.exists(self.path):
            return self._cache
        mtime = os.path.getmtime(self.path)
        if mtime != self._mtime:
            try:
                with open(self.path) as f:
                    self._cache = json.load(f)
                self._mtime = mtime
            except (OSError, json.JSONDecodeError):
                pass
        return self._cache

    def backends(self, role: str, group: Optional[str] = None) -> List[str]:
        """Addresses for a role. When the role's service declares LeaderOnly
        (KEP-260 sharedServiceSelection, carried into registry entries), only
        instance leaders are addressed — one endpoint per multi-host
        instance; the default (All) round-robins every pod."""
        all_, leaders, leader_only = [], [], False
        for fqdn, e in sorted(self.entries().items()):
            if e.get("role") == role and (group is None or e.get("group") == group):
                all_.append(e["addr"])
                leader_only = leader_only or bool(e.get("leaderOnly"))
                if e.get("leader", True):
                    leaders.append(e["addr"])
        return (leaders or all_) if leader_only else all_


class RouterState:
    def __init__(self, registry: Registry, group: Optional[str],
                 static_backends: Optional[dict] = None):
        self.registry = registry
        self.group = group
        self.static = static_backends or {}
        self._rr = {}
        self.metrics = {"requests": 0, "pd_requests": 0, "errors": 0,
                        "kv_bytes_routed": 0}

    def pick(self, role: str) -> Optional[str]:
        backends = self.static.get(role) or self.registry.backends(role, self.group)
        if not backends:
            return None
        i = self._rr.get(role, 0)
        self._rr[role] = i + 1
        return backends[i % len(backends)]

    def pd_mode(self) -> bool:
        return bool(
            (self.static.get("prefill") or self.registry.backends("prefill", self.group))
            and (self.static.get("decode") or self.registry.backends("decode", self.group))
        )


class Handler(socketserver.BaseRequestHandler):
    def handle(self):
        state: RouterState = self.server.state
        while True:
            try:
                obj, _, _ = recv_msg(self.request)
            except (ConnectionError, json.JSONDecodeError):
                return
            if obj is None:
                return
            op = obj.get("op")
            if op == "health":
                send_msg(self.request, {
                    "ok": True, "pd": state.pd_mode(),
                    "metrics": state.metrics,
                })
                continue
            if op == "embed":
                try:
                    addr = self._pick_worker(state)
                    resp, _, _ = request_once(addr, obj)
                    send_msg(self.request, resp or {"error": "no response"})
                except Exception as e:
                    send_msg(self.request, {"error": f"embed: {e}"})
                continue
            if op != "generate":
                send_msg(self.request, {"error": f"router: unsupported op {op!r}"})
                continue
            try:
                if obj.get("stream"):
                    self._generate_stream(state, obj)
                else:
                    send_msg(self.request, self._generate(state, obj))
            except Exception as e:
                state.metrics["errors"] += 1
                send_msg(self.request, {"error": str(e), "done": True})

    def _route(self, state: RouterState, obj: dict):
        """Resolve the backend leg shared by blocking and streaming paths.
        PD mode runs the (always blocking) prefill hop here; returns
        (addr, (header, k_bytes, v_bytes)) for the final leg."""
        state.metrics["requests"] += 1
        if state.pd_mode():
            state.metrics["pd_requests"] += 1
            # Forward sampling fields: the FIRST token is sampled by the
            # prefill engine — without them it would always be greedy,
            # diverging from unified mode for the identical request.
            pf_req = {"op": "prefill", "prompt": obj["prompt"]}
            for key in ("temperature", "top_k", "top_p", "min_p",
                        "repetition_penalty", "presence_penalty",
                        "frequency_penalty", "seed", "json_mode", "lora",
                        "stop_token"):
                if key in obj:
                    pf_req[key] = obj[key]
            hdr, kb, vb = request_once(state.pick("prefill"), pf_req)
            if hdr is None or "error" in hdr:
                raise RuntimeError(f"prefill failed: {hdr}")
            state.metrics["kv_bytes_routed"] += len(kb or b"") + len(vb or b"")
            fwd = dict(hdr)
            fwd["op"] = "decode_bundle"
            for key in ("max_new_tokens", "temperature", "top_k", "top_p",
                        "min_p", "repetition_penalty", "presence_penalty",
                        "frequency_penalty", "seed", "logprobs", "json_mode",
                        "lora", "stop_token", "stream"):
                if key in obj:
                    fwd[key] = obj[key]
            return state.pick("decode"), (fwd, kb, vb)
        return self._pick_worker(state), (obj, None, None)

    @staticmethod
    def _pick_worker(state: RouterState) -> str:
        """A unified-engine backend (embed / non-PD generate)."""
        worker = state.pick("worker") or state.pick("server")
        if worker is None:
            # fall back to any non-router role present
            roles = {e.get("role") for e in state.registry.entries().values()}
            roles.discard("router")
            for r in sorted(roles):
                worker = state.pick(r)
                if worker:
                    break
        if worker is None:
            raise RuntimeError("no backends available")
        return worker

    def _generate(self, state: RouterState, obj: dict) -> dict:
        t0 = time.perf_counter()
        pd = state.pd_mode()
        addr, payload = self._route(state, obj)
        resp, _, _ = request_once(addr, *payload)
        if resp is None:
            raise RuntimeError("backend closed connection")
        if pd:
            if "error" in resp:
                raise RuntimeError(f"decode failed: {resp}")
            resp["ttft_s"] = time.perf_counter() - t0
        return resp


    def _generate_stream(self, state: RouterState, obj: dict) -> None:
        """Streaming generate: relay incremental token frames from the
        backend to the client (feeds the SSE front end). PD mode streams
        the decode leg; the prefill leg is one blocking hop (its product is
        the first token + KV)."""
        import socket as _socket
        addr, payload = self._route(state, obj)
        host, port = addr.rsplit(":", 1)
        with _socket.create_connection((host, int(port)), timeout=300) as s:
            send_msg(s, *payload)
            while True:
                frame, _, _ = recv_msg(s)
                if frame is None:
                    raise RuntimeError("backend closed mid-stream")
                send_msg(self.request, frame)
                if frame.get("done") or "error" in frame:
                    return


class RouterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rbg-tpu-router")
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--registry", default=os.environ.get("RBG_REGISTRY_PATH"))
    ap.add_argument("--group", default=os.environ.get("RBG_GROUP_NAME"))
    ap.add_argument("--backends", default="",
                    help='static JSON {"prefill": ["host:port"], ...}')
    args = ap.parse_args(argv)
    port = int(os.environ.get("RBG_SERVE_PORT")
               or os.environ.get("RBG_PORT_SERVE") or args.port)
    static = json.loads(args.backends) if args.backends else None
    server = RouterServer(("127.0.0.1", port), Handler)
    server.state = RouterState(Registry(args.registry), args.group, static)
    print(f"router listening on 127.0.0.1:{port} group={args.group}", flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
