"""Router process — the front role of a PD-disagg group.

Reference analog: the sglang-router role in ``examples/inference/
pd-disagg-*.yaml`` (router → prefill → decode with startup dependencies;
the deployed router is cache-aware and fault-tolerant). Discovers its
backends from the address registry the executor maintains (or static
``--backends``):

* registry entries carry the role name, so PD mode switches on automatically
  when ``prefill`` and ``decode`` roles exist: prefill op → KV bundle over
  the wire → decode_bundle op on a decode peer (Mooncake-style transfer).
* otherwise routes ``generate`` over unified workers.

Resilience (reference parity with the deployed sglang-router):

* **least-outstanding-requests** backend choice per role (ties broken
  least-recently-picked), not blind round-robin;
* **health eviction**: a connect/transport failure evicts the backend with
  exponential backoff (1 s → 15 s); a background prober health-checks
  evicted backends every 500 ms and re-admits on first success;
* **failover retries**: every leg is idempotent here — prefill re-runs on a
  sibling, decode_bundle re-sends the held KV bundle, unified generate
  re-submits — so a dead backend never surfaces as a client error while a
  sibling lives;
* **deterministic replay**: sampled requests without a client seed get a
  router-assigned one, so a mid-stream failover re-runs the identical
  token stream on the sibling (position-keyed PRNG: randomness is
  f(seed, position)) and the router resumes the client stream exactly
  where it broke — already-delivered tokens are skipped, never replayed.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socket
import socketserver
import sys
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from rbg_tpu.engine.protocol import (CODE_DEADLINE, CODE_DRAINING,
                                     CODE_KV_STREAM,
                                     RETRYABLE_REJECT_CODES, recv_msg,
                                     request_once, send_msg)
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs import trace
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.obs.slo import SLOTargets, SLOTracker

MAX_ATTEMPTS = 3          # distinct backends tried per leg
CONNECT_TIMEOUT_S = 5.0   # fast failure detection on the connect
STREAM_TIMEOUT_S = 300.0  # per-recv budget once streaming
LEG_TIMEOUT_S = 120.0     # per-attempt blocking-call cap (deadline trims it)
DEFAULT_TIMEOUT_S = 120.0 # whole-request budget when the client sends none
AFFINITY_PREFIX = 32      # prompt tokens hashed for cache affinity
AFFINITY_SLACK = 4        # max extra outstanding before affinity yields
# Cache-aware scoring (Mooncake): a FULL prefill weighs this many
# outstanding-request equivalents in the candidate order; a prefix hit
# scales it down by the hit fraction, and a host-tier hit adds the
# promote-fetch time over the measured link (KV_COST_WEIGHT currency).
PREFIX_MISS_WEIGHT = 2.0
REPLICATE_HOTNESS = 8     # deepest-key lookups before a prefix is "hot"
REPLICATE_EVERY = 4       # every Nth hot single-holder lookup goes off-holder
# Off-holder routes attempted per prefix before giving up: a replica
# with no directory publish path never registers the second copy, and
# an unbounded tick would tax the hottest traffic with deliberate full
# prefills forever. A second holder appearing resets the count.
REPLICATE_MAX_PER_PREFIX = 3
# Transfer-cost-aware decode selection (NetKV, PAPERS.md): estimated
# KV-move seconds (bytes / measured link rate) are weighed against queue
# depth at this exchange rate — 1/WEIGHT seconds of transfer costs as much
# as one outstanding request. Rates come from rbg_kvtransfer link
# observations; with no measurement yet the default keeps the cost term
# small so least-outstanding still dominates.
KV_COST_WEIGHT = 4.0
DEFAULT_KV_LINK_RATE = 1e9   # bytes/s assumed before any real transfer


class _Rejected(Exception):
    """A structured upstream rejection (overloaded / draining / deadline)
    that must reach the client VERBATIM — wrapping it in a generic error
    string would strip the code and retry_after_s the edge maps to
    429/503/504 + Retry-After."""

    def __init__(self, frame: dict):
        super().__init__(frame.get("error", "rejected"))
        self.frame = dict(frame)


def _deadline_frame(msg: str) -> dict:
    return {"error": msg, "code": CODE_DEADLINE}


class RetryBudget:
    """Token bucket capping cross-backend retries router-wide. Under a shed
    storm every request retrying on every sibling MULTIPLIES load exactly
    when the fleet can least afford it — once the bucket is empty, failures
    surface immediately instead of amplifying. First attempts are never
    charged; rate=0 disables retries outright; rate=None disables the
    budget (unbounded legacy behavior)."""

    def __init__(self, rate: Optional[float] = 8.0, burst: float = 32.0):
        self.rate = rate
        # rate=0 means retries DISABLED — the bucket must start empty too,
        # or the initial burst would still allow `burst` retries.
        self.burst = 0.0 if rate == 0 else float(burst)
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def take(self) -> bool:
        if self.rate is None:
            return True
        now = time.monotonic()
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            ok = self._tokens >= 1.0
            if ok:
                self._tokens -= 1.0
            tokens = self._tokens
        # Published so operators can SEE the per-process bucket drain:
        # the budget is per-router-process, so a tier of N routers has an
        # N x fleet-wide effective budget (docs/operations.md).
        REGISTRY.set_gauge(obs_names.SERVING_RETRY_BUDGET_TOKENS, tokens)
        return ok

    def snapshot(self) -> dict:
        with self._lock:
            return {"rate_per_s": self.rate, "burst": self.burst,
                    "tokens": round(self._tokens, 2)}


class Registry:
    """Pod address registry: {fqdn: {addr, role, group}} JSON file, written
    atomically by the executor; re-read (mtime-cached) per lookup."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._cache: Dict[str, dict] = {}
        self._mtime = 0.0

    def entries(self) -> Dict[str, dict]:
        if not self.path or not os.path.exists(self.path):
            return self._cache
        mtime = os.path.getmtime(self.path)
        if mtime != self._mtime:
            try:
                with open(self.path) as f:
                    self._cache = json.load(f)
                self._mtime = mtime
            except (OSError, json.JSONDecodeError):
                pass
        return self._cache

    def backends(self, role: str, group: Optional[str] = None) -> List[str]:
        """Addresses for a role. When the role's service declares LeaderOnly
        (KEP-260 sharedServiceSelection, carried into registry entries), only
        instance leaders are addressed — one endpoint per multi-host
        instance; the default (All) addresses every pod."""
        all_, leaders, leader_only = [], [], False
        for fqdn, e in sorted(self.entries().items()):
            if e.get("role") == role and (group is None or e.get("group") == group):
                all_.append(e["addr"])
                leader_only = leader_only or bool(e.get("leaderOnly"))
                if e.get("leader", True):
                    leaders.append(e["addr"])
        return (leaders or all_) if leader_only else all_


# Every registry family carrying a per-backend `backend=` label — the
# staleness sweep in BackendPool.retain prunes these for dead addresses.
_BACKEND_SERIES = (obs_names.ROUTER_BACKEND_OUTSTANDING,
                   obs_names.ROUTER_BACKEND_DRAINING,
                   obs_names.SLO_JUDGED_TOTAL,
                   obs_names.SLO_TTFT_MET_TOTAL,
                   obs_names.SLO_TPOT_MET_TOTAL,
                   obs_names.SLO_GOODPUT_TOTAL,
                   obs_names.SLO_TTFT_SECONDS,
                   obs_names.SLO_TPOT_SECONDS)


class _BackendState:
    __slots__ = ("outstanding", "fails", "down_until", "last_pick",
                 "draining")

    def __init__(self):
        self.outstanding = 0
        self.fails = 0
        self.down_until = 0.0
        self.last_pick = 0
        self.draining = False


class BackendPool:
    """Health + load bookkeeping for backend addresses.

    Selection is least-outstanding-requests over healthy backends (ties:
    least recently picked). A transport failure evicts the address with
    exponential backoff; recovery re-admits it (via the prober, or lazily
    when the backoff expires). When EVERY candidate is evicted the
    soonest-to-recover one is still returned — total eviction must degrade
    to "keep trying", not a hard outage."""

    EVICT_BASE_S = 1.0
    EVICT_MAX_S = 15.0

    def __init__(self, on_unavailable=None):
        self._lock = threading.Lock()
        self._st: Dict[str, _BackendState] = {}
        self._seq = 0
        # Fired (outside the lock) when an address stops being a routing
        # candidate — drain mark or eviction. The router wires it to
        # prefix-affinity demotion: a draining/preempted backend must fall
        # out of the front-of-LRU IMMEDIATELY, not when it gets evicted.
        self._on_unavailable = on_unavailable

    def _state(self, addr: str) -> _BackendState:
        st = self._st.get(addr)
        if st is None:
            st = self._st[addr] = _BackendState()
        return st

    def order(self, addrs: List[str], cost=None) -> List[str]:
        """Candidates in try-order: healthy by (outstanding + transfer
        cost, last_pick), then DRAINING by the same key (not-a-candidate
        while any healthy sibling exists, but still reachable so a
        fleet-wide rollout degrades to 'draining' replies rather than a
        hard outage), then evicted by soonest recovery.

        ``cost`` (optional ``addr -> float``) is the transfer-cost term of
        the NetKV-style decode selection: estimated KV-move seconds scaled
        into outstanding-equivalents. Healthy candidates only — a cheap
        link never un-drains or un-evicts anything."""
        now = time.monotonic()
        costs = {a: cost(a) for a in addrs} if cost is not None else {}
        with self._lock:
            healthy, draining, down = [], [], []
            for i, a in enumerate(addrs):
                st = self._state(a)
                if st.down_until > now:
                    down.append((st.down_until, i, a))
                elif st.draining:
                    draining.append((st.outstanding, st.last_pick, i, a))
                else:
                    healthy.append((st.outstanding + costs.get(a, 0.0),
                                    st.last_pick, i, a))
            healthy.sort()
            draining.sort()
            down.sort()
            return ([t[-1] for t in healthy] + [t[-1] for t in draining]
                    + [t[-1] for t in down])

    def acquire(self, addr: str) -> None:
        # last_pick is charged HERE — to the address actually served —
        # not in order(): affinity reordering can choose a different head
        # than order() computed, and crediting the unserved sibling would
        # invert the least-recently-picked tie-break.
        with self._lock:
            st = self._state(addr)
            st.outstanding += 1
            self._seq += 1
            st.last_pick = self._seq
            # Published INSIDE the lock: concurrent acquires on one addr
            # would otherwise commit their gauge writes out of order and
            # park a stale value (the Registry lock is a plain leaf lock
            # — no ordering hazard nesting it here).
            REGISTRY.set_gauge(obs_names.ROUTER_BACKEND_OUTSTANDING,
                               float(st.outstanding), backend=addr)

    def release(self, addr: str) -> None:
        with self._lock:
            st = self._state(addr)
            st.outstanding = max(0, st.outstanding - 1)
            REGISTRY.set_gauge(obs_names.ROUTER_BACKEND_OUTSTANDING,
                               float(st.outstanding), backend=addr)

    def ok(self, addr: str) -> None:
        with self._lock:
            st = self._state(addr)
            st.fails = 0
            st.down_until = 0.0

    def fail(self, addr: str) -> None:
        with self._lock:
            st = self._state(addr)
            st.fails += 1
            backoff = min(self.EVICT_BASE_S * (2 ** (st.fails - 1)),
                          self.EVICT_MAX_S)
            st.down_until = time.monotonic() + backoff
        # Outside the lock: an evicted (dead / preempted) backend must
        # lose its prefix-affinity front-of-LRU spot immediately.
        if self._on_unavailable is not None:
            self._on_unavailable(addr)

    def set_draining(self, addr: str, draining: bool) -> None:
        """Mark an address as draining (SIGTERM rollout): it stops being a
        candidate while siblings live but is NOT evicted — its in-flight
        streams finish, and probes clear the flag if the pod un-drains
        (or the address never returns and ordinary eviction takes over)."""
        with self._lock:
            self._state(addr).draining = draining
            REGISTRY.set_gauge(obs_names.ROUTER_BACKEND_DRAINING,
                               1.0 if draining else 0.0, backend=addr)
        if draining and self._on_unavailable is not None:
            self._on_unavailable(addr)

    def is_draining(self, addr: str) -> bool:
        with self._lock:
            return self._state(addr).draining

    def draining(self) -> List[str]:
        with self._lock:
            return [a for a, st in self._st.items() if st.draining]

    def evicted(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return [a for a, st in self._st.items() if st.down_until > now]

    def outstanding(self, addr: str) -> int:
        with self._lock:
            return self._state(addr).outstanding

    def is_down(self, addr: str) -> bool:
        with self._lock:
            return self._state(addr).down_until > time.monotonic()

    def probe(self, timeout: float = 1.0) -> List[str]:
        """Health-check every evicted backend (re-admit responders) and
        every draining backend (clear the flag if it un-drained; a drained
        process that already exited fails its next dispatch and moves to
        ordinary eviction). Returns the re-admitted addresses."""
        readmitted = []
        for addr in self.evicted():
            try:
                resp, _, _ = request_once(addr, {"op": "health"},
                                          timeout=timeout)
            except (OSError, ConnectionError, json.JSONDecodeError):
                continue
            if resp and resp.get("ok"):
                self.ok(addr)
                self.set_draining(addr, bool(resp.get("draining")))
                readmitted.append(addr)
        for addr in self.draining():
            try:
                resp, _, _ = request_once(addr, {"op": "health"},
                                          timeout=timeout)
            except (OSError, ConnectionError, json.JSONDecodeError):
                continue
            if resp and resp.get("ok") and not resp.get("draining"):
                self.set_draining(addr, False)
        return readmitted

    def retain(self, live) -> None:
        """Drop state for addresses no longer in the registry (pod churn
        mints a new address per replacement — without pruning, a long-lived
        router's state and health payload grow monotonically). In-flight
        entries are kept until their requests drain."""
        with self._lock:
            for a in [a for a in self._st
                      if a not in live and self._st[a].outstanding == 0]:
                del self._st[a]
            keep = set(self._st) | set(live)
        # Series staleness: an evicted address must leave the exposition
        # too, or a long-lived router on a churning fleet renders every
        # dead pod's series forever — the pool gauges AND the backend-
        # labeled rbg_slo_* verdicts the router's judgment minted. Swept
        # against the registry's ACTUAL label values (not a drop list):
        # a judgment that lands after its request's release — and so
        # re-mints series for an address already pruned from _st — is
        # caught by the next sweep instead of leaking permanently.
        for name in _BACKEND_SERIES:
            for a in REGISTRY.label_values(name, "backend") - keep:
                REGISTRY.remove_series(name, backend=a)

    def snapshot(self) -> Dict[str, dict]:
        now = time.monotonic()
        with self._lock:
            return {a: {"outstanding": st.outstanding, "fails": st.fails,
                        "down_for_s": round(max(0.0, st.down_until - now), 3),
                        "draining": st.draining}
                    for a, st in self._st.items()}


class PrefixAffinity:
    """Cache-aware routing memory (the sglang-router property VERDICT r4
    #4 named): requests sharing a prompt prefix go to the backend whose
    radix / prefix cache already holds it. Approximation: an LRU map from
    hash(first AFFINITY_PREFIX tokens) → the backend that last served
    that prefix. The *balance guard* lives in the caller — affinity only
    wins while the remembered backend isn't meaningfully busier than the
    least-loaded one, so a hot prefix cannot melt a single replica."""

    def __init__(self, cap: int = 4096):
        self.cap = cap
        self._m: "OrderedDict[int, str]" = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def key(prompt) -> Optional[int]:
        if not prompt:
            return None
        return hash(tuple(prompt[:AFFINITY_PREFIX]))

    def get(self, key: Optional[int]) -> Optional[str]:
        if key is None:
            return None
        with self._lock:
            addr = self._m.get(key)
            if addr is not None:
                self._m.move_to_end(key)
            return addr

    def put(self, key: Optional[int], addr: str) -> None:
        if key is None:
            return
        with self._lock:
            self._m[key] = addr
            self._m.move_to_end(key)
            if len(self._m) > self.cap:
                self._m.popitem(last=False)

    def drop_backend(self, addr: str) -> int:
        """Demote every prefix remembered for ``addr`` — the drain /
        disruption staleness fix: a draining or preempted backend used to
        stay front-of-LRU until eviction aged it out, steering prefix
        traffic at a pod that refuses (or dropped) it."""
        with self._lock:
            dead = [k for k, a in self._m.items() if a == addr]
            for k in dead:
                del self._m[k]
            return len(dead)


class RouterState:
    def __init__(self, registry: Registry, group: Optional[str],
                 static_backends: Optional[dict] = None,
                 token: Optional[str] = None,
                 retry_budget: Optional[RetryBudget] = None,
                 slo_targets: Optional[SLOTargets] = None,
                 directory=None, kv_stream: bool = True,
                 router_id: Optional[str] = None, tier=None):
        from rbg_tpu.kvtransfer.transport import LinkStats

        self.registry = registry
        self.group = group
        # Router-tier membership (engine/routertier.py): this router's
        # stable identity on the hash ring plus the peer event feed it
        # publishes health/draining/link-rate/ingress transitions to.
        # None = standalone single-router deployment, nothing changes.
        self.router_id = router_id or "router-0"
        self.tier = tier
        # PR-2 drain protocol, router edition: a draining router finishes
        # in-flight streams (tracked below) while refusing NEW requests
        # with a structured CODE_DRAINING frame — tier peers take its
        # hash ranges the moment the drain transition hits the feed.
        self.draining = False
        self._active_requests = 0
        self._drain_lock = threading.Lock()
        if tier is not None:
            tier.register(self.router_id, state=self)
        self.static = static_backends or {}
        # Drain/eviction notifications demote prefix affinity immediately
        # (the staleness fix) — wired before any traffic.
        self.pool = BackendPool(on_unavailable=self._backend_unavailable)
        # Cluster prefix directory (kvtransfer.directory): lets prefix
        # affinity route to ANY replica holding the prefix, not just the
        # last-serving one. Optional — lookups degrade to the local LRU.
        self.directory = directory
        # Chunked prefill→decode KV streaming (push_to): on by default;
        # backends that don't support it reply with a bundle and nothing
        # changes.
        self.kv_stream = kv_stream
        # Measured prefill→decode link rates (merged from prefill replies'
        # observed push rates) feeding transfer-cost-aware decode choice.
        self.linkstats = LinkStats("router")
        # Observed KV bytes per prompt token (EWMA) — the pre-prefill
        # estimate the stream-mode decode choice scores with.
        self._kv_bpt: Optional[float] = None
        # Router-level SLO judgment (obs/slo.py): TTFT/TPOT measured from
        # the INGRESS arrival stamp — a retried or failed-over request is
        # charged its full wait — aggregated per role and per backend
        # into the health snapshot.
        self.slo = SLOTracker(slo_targets or SLOTargets(),
                              component="router")
        # Shared data-plane bearer token (VERDICT r4 #6): when set, clients
        # must present it and the router forwards it on every backend leg
        # (one trust domain edge-to-engine; health stays open for probes).
        self.token = token if token is not None \
            else (os.environ.get("RBG_DATA_TOKEN") or None)
        self.affinity = PrefixAffinity()
        self.retry_budget = retry_budget or RetryBudget()
        # Topology candidacy: roles withdrawn from NEW-traffic routing by
        # the adaptive agg↔disagg controller (in-flight work on their
        # backends finishes untouched; set membership is GIL-atomic).
        self._inactive_roles: set = set()
        # Hot-prefix replication cadence (single counter; GIL-atomic
        # increments — an off-by-one under a race only shifts WHICH
        # lookup replicates, never whether replication happens) plus a
        # bounded per-prefix attempt ledger (akey -> off-holder routes)
        # so a fleet that never registers the second copy stops paying
        # the deliberate-miss tax after REPLICATE_MAX_PER_PREFIX tries.
        self._replicate_seq = 0
        self._replicated: "OrderedDict[int, int]" = OrderedDict()
        self.metrics = {"requests": 0, "pd_requests": 0, "errors": 0,
                        "retries": 0, "failovers": 0, "affinity_hits": 0,
                        "kv_bytes_routed": 0,
                        # KV transfer plane (kvtransfer): streamed PD
                        # requests, bundle fallbacks after a stream
                        # failure, cluster prefix-directory hits, and
                        # affinity entries demoted on drain/eviction.
                        "kv_stream_routed": 0, "kv_stream_fallbacks": 0,
                        "directory_hits": 0, "affinity_demotions": 0,
                        "dir_replications": 0,
                        # Overload / lifecycle robustness counters.
                        "sheds_routed_around": 0, "sheds_returned": 0,
                        "draining_routed_around": 0,
                        "deadline_refusals": 0,
                        "retry_budget_exhausted": 0}

    def _backend_unavailable(self, addr: str) -> None:
        dropped = self.affinity.drop_backend(addr)
        if dropped:
            self.metrics["affinity_demotions"] += dropped
        self._tier_publish("health", {"backend": addr, "available": False})

    # -- router tier seam (engine/routertier.py) --

    def _tier_publish(self, kind: str, payload: dict) -> None:
        if self.tier is None:
            return
        try:
            self.tier.publish(self.router_id, kind, payload)
        except Exception:
            pass

    def note_ingress(self, kind: str, n: float) -> None:
        """One ingress token observation (prefill prompt tokens at
        dispatch / decode tokens at delivery) — counted in THIS process's
        registry AND in the tier aggregate, because the topology ratio
        must see the whole tier's mix, not one router's shard of it."""
        if n <= 0:
            return
        REGISTRY.inc(obs_names.ROUTER_INGRESS_TOKENS_TOTAL, float(n),
                     kind=kind)
        if self.tier is not None:
            try:
                self.tier.note_ingress(self.router_id, kind, float(n))
            except Exception:
                pass

    def publish_ingress(self) -> None:
        """Publish this process's CUMULATIVE ingress token counters on
        the tier feed (the wire-form twin of ``note_ingress`` for members
        whose tier object is remote). Cumulative on purpose: the tier
        folds watermark deltas, and a restart that zeroes these counters
        reads as a counter restart (full-value fold), never a negative
        delta — the PR-8 convention, now load-bearing for the ratio."""
        totals = {k: REGISTRY.counter(obs_names.ROUTER_INGRESS_TOKENS_TOTAL,
                                      kind=k)
                  for k in ("prefill", "decode")}
        self._tier_publish("ingress", {"totals": totals})

    def on_peer_event(self, ev: dict) -> None:
        """Receive one router-to-router feed event: peers' backend
        health/draining transitions and measured link rates fold into
        THIS router's pool and link view, so N routers converge on one
        picture of the fleet instead of each rediscovering it."""
        kind, payload = ev.get("kind"), ev.get("payload") or {}
        addr = payload.get("backend")
        if kind == "link_rates":
            self.merge_link_rates(payload.get("rates"), _from_peer=True)
        elif kind == "draining" and addr:
            self.pool.set_draining(addr, bool(payload.get("draining")))
        elif kind == "health" and addr:
            if payload.get("available"):
                self.pool.ok(addr)
            else:
                self.pool.fail(addr)

    # -- drain protocol (SIGTERM → finish in-flight, refuse new) --

    def enter_request(self) -> bool:
        """Admission gate for one request: False when draining (caller
        replies with the structured CODE_DRAINING frame)."""
        with self._drain_lock:
            if self.draining:
                return False
            self._active_requests += 1
            return True

    def exit_request(self) -> None:
        with self._drain_lock:
            if self._active_requests > 0:
                self._active_requests -= 1

    def begin_drain(self, wait_s: float = 30.0) -> bool:
        """Flip to draining, announce it on the tier feed (peers take
        this router's hash ranges), then wait for in-flight streams to
        finish. Returns True when the router drained clean inside
        ``wait_s``."""
        with self._drain_lock:
            self.draining = True
        if self.tier is not None:
            try:
                self.tier.set_draining(self.router_id, True)
            except Exception:
                pass
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            with self._drain_lock:
                if self._active_requests == 0:
                    return True
            time.sleep(0.02)
        with self._drain_lock:
            return self._active_requests == 0

    def charge_retry(self) -> bool:
        """Take one retry token; on exhaustion count it and refuse."""
        if self.retry_budget.take():
            return True
        self.metrics["retry_budget_exhausted"] += 1
        return False

    def note_shed(self, addr: str, frame: dict,
                  best: Optional[dict]) -> dict:
        """Record a structured route-around shed (overloaded / draining)
        from a HEALTHY backend — the one shed policy both the blocking and
        streaming paths apply: no eviction, draining marks the pool, and
        the frame with the smallest retry_after_s becomes the reply should
        every candidate shed."""
        self.pool.ok(addr)
        if frame.get("code") == CODE_DRAINING:
            self.pool.set_draining(addr, True)
            self.metrics["draining_routed_around"] += 1
            # Tier peers learn the drain NOW instead of each waiting to
            # be shed by the same backend themselves.
            self._tier_publish("draining", {"backend": addr,
                                            "draining": True})
        else:
            self.metrics["sheds_routed_around"] += 1
        if best is None or (frame.get("retry_after_s") or 1e9) < \
                (best.get("retry_after_s") or 1e9):
            return frame
        return best

    def authorized(self, obj: dict) -> bool:
        if not self.token:
            return True
        from rbg_tpu.engine.protocol import token_ok
        return token_ok(obj.get("token"), self.token)

    def set_role_candidacy(self, role: str, active: bool) -> None:
        """Topology cutover seam: an inactive role's backends take no NEW
        requests, while streams they already hold run to completion."""
        if active:
            self._inactive_roles.discard(role)
        else:
            self._inactive_roles.add(role)

    def role_active(self, role: str) -> bool:
        return role not in self._inactive_roles

    def candidates(self, role: str, cost=None) -> List[str]:
        if role in self._inactive_roles:
            return []
        backends = self.static.get(role) or self.registry.backends(role, self.group)
        live = {a for addrs in self.static.values() for a in addrs}
        live.update(e["addr"] for e in self.registry.entries().values()
                    if "addr" in e)
        self.pool.retain(live)
        return self.pool.order(list(backends), cost=cost)

    # -- transfer-cost-aware decode selection (NetKV) --

    def kv_cost_fn(self, kv_bytes: int):
        """``addr -> outstanding-equivalents`` for moving ``kv_bytes`` to
        that backend, from MEASURED link rates (None when there is
        nothing to weigh)."""
        if not kv_bytes:
            return None

        def cost(addr: str) -> float:
            rate = self.linkstats.rate(addr) or DEFAULT_KV_LINK_RATE
            return (kv_bytes / rate) * KV_COST_WEIGHT
        return cost

    def est_kv_bytes(self, prompt_tokens: int) -> int:
        """Pre-prefill KV size estimate from observed bytes/token."""
        if self._kv_bpt is None:
            return 0
        return int(self._kv_bpt * prompt_tokens)

    def note_kv_observed(self, prompt_tokens: int, kv_bytes: int) -> None:
        if not prompt_tokens or not kv_bytes:
            return
        bpt = kv_bytes / prompt_tokens
        self._kv_bpt = bpt if self._kv_bpt is None \
            else 0.7 * self._kv_bpt + 0.3 * bpt

    def merge_link_rates(self, rates: Optional[dict],
                         _from_peer: bool = False) -> None:
        """Fold prefill-reported push rates (prefill→decode, observed on
        real transfers) into this router's link view. Locally-observed
        rates (not peer echoes) are re-published on the tier feed so
        every router's NetKV decode choice prices the same links."""
        if not rates:
            return
        for addr, rate in rates.items():
            try:
                self.linkstats.observe(addr, int(float(rate)), 1.0)
            except (TypeError, ValueError):
                continue
        if not _from_peer:
            self._tier_publish("link_rates", {"rates": dict(rates)})

    def pd_mode(self) -> bool:
        return bool(
            self.role_active("prefill") and self.role_active("decode")
            and (self.static.get("prefill") or self.registry.backends("prefill", self.group))
            and (self.static.get("decode") or self.registry.backends("decode", self.group))
        )

    def worker_role(self) -> str:
        """The unified-engine role (embed / non-PD generate)."""
        for role in ("worker", "server"):
            if self.role_active(role) and (
                    self.static.get(role)
                    or self.registry.backends(role, self.group)):
                return role
        roles = {e.get("role") for e in self.registry.entries().values()}
        roles |= set(self.static)
        roles.discard("router")
        roles.discard(None)
        for r in sorted(roles):
            if self.role_active(r) and (
                    self.static.get(r)
                    or self.registry.backends(r, self.group)):
                return r
        raise RuntimeError("no backends available")

    def _affinity_viable(self, addr: Optional[str],
                         cands: List[str]) -> bool:
        """A cache-affinity candidate wins only while it is a live,
        non-draining candidate that is not meaningfully busier than the
        least-loaded choice — a hot prefix cannot melt one replica, and a
        draining/preempted backend is never fronted."""
        return bool(addr and addr in cands and addr != cands[0]
                    and not self.pool.is_down(addr)
                    and not self.pool.is_draining(addr)
                    and self.pool.outstanding(addr)
                    <= self.pool.outstanding(cands[0]) + AFFINITY_SLACK)

    def _prefix_cost_fn(self, prompt, matched_tokens: int,
                        detail: List[dict], akey=None):
        """``addr -> outstanding-equivalents`` of serving this prompt's
        prefill there, from the cluster directory's tier-tagged holder
        detail: a device-tier holder's hit is ~free (only the unmatched
        tail costs), a host-tier holder adds the promote fetch (estimated
        bytes over its measured link rate — the PR-10 KV-move currency),
        and a non-holder pays the full prefill. Hot single-holder
        prefixes are deliberately scored as misses every
        ``REPLICATE_EVERY``-th lookup, so the least-loaded non-holder
        computes AND registers the prefix — a second replica appears
        without any explicit copy protocol. Returns ``(cost_fn,
        replicate_tick, holder_addrs)`` — the caller counts a
        replication only when the tick actually routed off-holder."""
        entries = {e["backend"]: e for e in detail if e.get("backend")}
        replicate = False
        if len(entries) == 1 and any(
                e.get("hotness", 0) >= REPLICATE_HOTNESS
                for e in entries.values()):
            if (akey is None or self._replicated.get(akey, 0)
                    < REPLICATE_MAX_PER_PREFIX):
                self._replicate_seq += 1
                replicate = self._replicate_seq % REPLICATE_EVERY == 0
        elif akey is not None and len(entries) > 1:
            # A second holder appeared: replication CONVERGED for this
            # prefix — forget the attempt count so a later holder loss
            # can re-replicate.
            self._replicated.pop(akey, None)
        hit_fraction = min(1.0, matched_tokens / max(1, len(prompt)))

        def cost(addr: str) -> float:
            e = entries.get(addr)
            if replicate:
                # Replication tick: the holder scores as a miss and the
                # non-holders as hits, so the least-loaded NON-holder
                # wins (unless it is much busier), computes the prefix,
                # and registers the second copy.
                return PREFIX_MISS_WEIGHT if e is not None else 0.0
            if e is None:
                return PREFIX_MISS_WEIGHT
            c = PREFIX_MISS_WEIGHT * (1.0 - hit_fraction)
            if e.get("tier") == "host":
                bytes_ = self.est_kv_bytes(matched_tokens)
                rate = self.linkstats.rate(addr) or DEFAULT_KV_LINK_RATE
                c += (bytes_ / rate) * KV_COST_WEIGHT
            return c
        return cost, replicate, frozenset(entries)

    # hot_path
    def candidates_for(self, role: str, prompt) -> List[str]:
        """Candidates ordered CACHE-AWARE. The local last-serving LRU
        stays the FAST PATH: a viable affinity hit answers with zero I/O
        — against a wire directory (``DirectoryClient``) the scored path
        costs a blocking RPC per request, which must only be paid when
        the LRU has nothing (the pre-hierarchy contract). On an LRU
        miss, the cluster directory scores every candidate prefix-hit
        depth × tier-fetch cost AGAINST its queue depth
        (``_prefix_cost_fn`` — the balance guard is the scoring itself:
        a deep hit on a swamped replica loses to a shallow miss on an
        idle one). Without a directory the LRU is all there is, under
        the legacy AFFINITY_SLACK balance guard."""
        cands = self.candidates(role)
        akey = PrefixAffinity.key(prompt)
        if akey is None or len(cands) < 2:
            return cands
        addr = self.affinity.get(akey)
        if self._affinity_viable(addr, cands):
            self.metrics["affinity_hits"] += 1
            return [addr] + [a for a in cands if a != addr]
        if addr == cands[0] and addr is not None:
            self.metrics["affinity_hits"] += 1
            return cands
        if self.directory is not None and prompt:
            try:
                matched, detail = self.directory.lookup_detail(list(prompt))
            except (OSError, RuntimeError, ValueError):
                matched, detail = 0, []
            if matched and detail:
                cost, replicate, holders = self._prefix_cost_fn(
                    prompt, matched, detail, akey=akey)
                # Reorder the list already built above — rebuilding via
                # candidates() would repeat the registry read + pool
                # retain on a hot path that just paid a directory RPC.
                scored = self.pool.order(list(cands), cost=cost)
                if scored and scored[0] in holders:
                    self.metrics["directory_hits"] += 1
                elif scored and replicate:
                    # Counted only when the inverted scoring ACTUALLY
                    # routed off-holder (a single-backend role, or a
                    # much-less-loaded holder, replicates nothing) —
                    # and the per-prefix ledger bounds the attempts.
                    self.metrics["dir_replications"] += 1
                    REGISTRY.inc(obs_names.KVT_DIR_REPLICATIONS_TOTAL)
                    self._replicated[akey] = \
                        self._replicated.get(akey, 0) + 1
                    self._replicated.move_to_end(akey)
                    while len(self._replicated) > 1024:
                        self._replicated.popitem(last=False)
                if scored:
                    return scored
        return cands

    def call(self, role: str, obj: dict, k_bytes=None, v_bytes=None,
             timeout: float = LEG_TIMEOUT_S, prompt=None,
             deadline: Optional[float] = None,
             pinned: Optional[str] = None,
             kv_bytes: int = 0) -> Tuple[str, dict, bytes, bytes]:
        """One blocking request with failover across the role's backends.
        Transport failures (connect refused, peer closed) evict + retry on
        a sibling; application errors pass through untouched. ``prompt``
        (when given) engages cache-affinity candidate ordering.

        ``deadline`` (absolute monotonic) is the REQUEST's end-to-end
        budget: every attempt — first dispatch or failover — derives its
        transport timeout from what remains instead of the fixed leg cap,
        the remaining budget is forwarded to the backend as ``timeout_s``
        (so ITS queue/abort enforcement composes), and a spent budget
        refuses the dispatch outright (``_Rejected`` with
        deadline_exceeded) — never a doomed retry.

        Structured sheds (code overloaded/draining) are NOT backend
        failures: the backend is healthy and answered. The router tries a
        sibling (retry-budget permitting) and, when every candidate shed,
        raises ``_Rejected`` carrying the frame with the smallest
        retry_after_s — the edge maps it to 429/503 + Retry-After.

        ``pinned`` restricts the leg to ONE address (a decode_stream leg —
        the KV lives only there; failover is the caller's re-route).
        ``kv_bytes`` engages transfer-cost-aware candidate ordering: the
        estimated move time over each backend's MEASURED link rate is
        weighed against its queue depth."""
        if pinned is not None:
            cands = [pinned]
        elif kv_bytes:
            cands = self.candidates(role, cost=self.kv_cost_fn(kv_bytes))
        else:
            cands = self.candidates_for(role, prompt)
        if not cands:
            raise RuntimeError(f"no {role} backends available")
        akey = PrefixAffinity.key(prompt)
        rspan = trace.current()     # ambient request span (NULL when off)
        last: Optional[Exception] = None
        shed: Optional[dict] = None
        for i, addr in enumerate(cands[:MAX_ATTEMPTS]):
            aspan = rspan.child(obs_names.SPAN_ROUTER_ATTEMPT,
                                backend=addr, attempt=i, role=role)
            if aspan and k_bytes is not None:
                aspan.attrs["kv_bytes"] = len(k_bytes) + len(v_bytes or b"")
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.metrics["deadline_refusals"] += 1
                    aspan.end(outcome="deadline")
                    raise _Rejected(_deadline_frame(
                        f"deadline spent before dispatch to {role} "
                        f"(attempt {i + 1})"))
                timeout = min(LEG_TIMEOUT_S, remaining)
                obj = dict(obj)
                obj["timeout_s"] = round(remaining, 3)
            if aspan:
                # Per-attempt child context: the backend's engine.op span
                # parents under THIS attempt, so sibling retries stay
                # distinguishable in the waterfall.
                obj = dict(obj)
                obj["trace"] = aspan.wire()
            if i:
                if not self.charge_retry():
                    aspan.end(outcome="retry_budget_exhausted")
                    break
                self.metrics["retries"] += 1
            self.pool.acquire(addr)
            t_dispatch = time.monotonic()
            try:
                resp, rk, rv = request_once(addr, obj, k_bytes, v_bytes,
                                            timeout=timeout)
            except (OSError, ConnectionError, json.JSONDecodeError) as e:
                self.pool.fail(addr)
                aspan.end(outcome="transport_error")
                last = e
                continue
            finally:
                self.pool.release(addr)
            if resp is None:
                self.pool.fail(addr)
                aspan.end(outcome="transport_error")
                last = RuntimeError(f"{addr} closed connection")
                continue
            code = resp.get("code")
            if code == CODE_DEADLINE:
                # The backend spent the client's budget (queue drop or
                # mid-run abort): structured passthrough — a sibling retry
                # would dispatch work that is already out of time.
                self.pool.ok(addr)
                aspan.end(outcome="deadline")
                raise _Rejected(resp)
            if code in RETRYABLE_REJECT_CODES:
                shed = self.note_shed(addr, resp, shed)
                aspan.end(outcome=code)
                continue
            self.pool.ok(addr)
            self.affinity.put(akey, addr)
            if i:
                self.metrics["failovers"] += 1
            aspan.end(outcome="ok")
            # Private timing stamp: when the SUCCESSFUL attempt was
            # dispatched (monotonic). Callers pop it to anchor TTFT at
            # ingress arrival — a backend-reported ttft_s alone restarts
            # the clock on every failover attempt and under-reports.
            if isinstance(resp, dict):
                resp["_router_t_dispatch"] = t_dispatch
            return addr, resp, rk, rv
        if shed is not None:
            self.metrics["sheds_returned"] += 1
            raise _Rejected(shed)
        raise RuntimeError(
            f"all {role} backends failed (tried {min(len(cands), MAX_ATTEMPTS)}): {last}")


class _ClientGone(Exception):
    """The CLIENT socket failed mid-relay. Deliberately NOT an OSError
    subclass: the failover loop catches transport errors and charges them
    to the backend — a vanished client must neither evict a healthy
    backend nor trigger a pointless replay on a sibling."""


class Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            self._serve_connection()
        except _ClientGone:
            # Routine client disconnect — not a router error, no traceback.
            return

    def _serve_connection(self):
        state: RouterState = self.server.state
        while True:
            try:
                obj, _, _ = recv_msg(self.request)
            except (ConnectionError, json.JSONDecodeError):
                return
            if obj is None:
                return
            op = obj.get("op")
            if op == "health":
                # Liveness itself stays unauthenticated, but on a
                # token-gated router the metrics and the backend pool
                # snapshot (internal topology addresses) are only for
                # authenticated peers — health must not map the very
                # fleet the token protects.
                resp = {"ok": True, "pd": state.pd_mode(),
                        "draining": state.draining,
                        "router_id": state.router_id}
                if state.authorized(obj):
                    # Candidacy is fleet topology — authenticated peers
                    # only, like the backend snapshot below.
                    if state._inactive_roles:
                        resp["inactive_roles"] = sorted(
                            state._inactive_roles)
                    resp["metrics"] = state.metrics
                    resp["backends"] = state.pool.snapshot()
                    resp["draining_backends"] = state.pool.draining()
                    resp["retry_budget"] = state.retry_budget.snapshot()
                    # KV transfer plane posture: streaming mode, measured
                    # per-backend link rates, observed KV bytes/token.
                    resp["kv"] = {
                        "stream": state.kv_stream,
                        "directory": state.directory is not None,
                        "link_rates": {
                            a: round(r, 1) for a, r in
                            state.linkstats.snapshot().items()},
                        "kv_bytes_per_token": (
                            round(state._kv_bpt, 1)
                            if state._kv_bpt is not None else None),
                    }
                    # Measured SLO attainment from THIS router's vantage
                    # (ingress-anchored TTFT): per role and per backend,
                    # 60 s window — the agg↔disagg switcher's decision
                    # input.
                    resp["slo"] = {
                        "targets": state.slo.targets.as_dict(),
                        "judged_total": state.slo.judged_total(),
                        "per_role": state.slo.attainment(
                            60.0, group_by=("role",)),
                        "per_backend": state.slo.attainment(
                            60.0, group_by=("backend",)),
                    }
                self._send_client(resp)
                continue
            if op in ("embed", "generate") and not state.authorized(obj):
                self._send_client({"error": "unauthorized", "done": True})
                continue
            try:
                deadline = self._stamp_deadline(obj)
            except (TypeError, ValueError) as e:
                self._send_client({"error": f"bad timeout_s: {e}",
                                   "done": True})
                continue
            if not state.enter_request():
                # SIGTERM drain: in-flight streams run to completion
                # (they passed this gate already); NEW work gets the
                # structured draining frame — the same shed contract the
                # backends use, so clients/peers route around.
                self._send_client({"error": "router draining",
                                   "code": CODE_DRAINING,
                                   "retry_after_s": 1.0, "done": True})
                continue
            try:
                self._dispatch_op(state, op, obj, deadline)
            finally:
                state.exit_request()

    def _dispatch_op(self, state: "RouterState", op: str, obj: dict,
                     deadline: float) -> None:
        # Ingress arrival stamp (the PR-2 deadline's sibling): TTFT is
        # measured from HERE — spanning queueing, the prefill leg, and
        # every failover attempt — never restarted per attempt.
        t_arrival = time.monotonic()
        # The router continues the edge's trace context — or IS the
        # ingress (head sampling) when clients hit it directly. The
        # incoming context is consumed here; every downstream leg gets
        # a fresh per-attempt child context instead.
        rspan = trace.from_wire(obj.pop("trace", None),
                                obs_names.SPAN_ROUTER_REQUEST, op=op)
        if op == "embed":
            state.metrics["requests"] += 1
            try:
                with trace.use_span(rspan):
                    _, resp, _, _ = state.call(state.worker_role(), obj,
                                               deadline=deadline)
            except _Rejected as e:
                resp = e.frame
            except Exception as e:
                state.metrics["errors"] += 1
                resp = {"error": f"embed: {e}"}
            resp.pop("_router_t_dispatch", None)
            rspan.end(outcome=resp.get("code") or
                      ("error" if "error" in resp else "ok"))
            self._send_client(resp)
            return
        if op != "generate":
            rspan.end(outcome="unsupported_op")
            self._send_client({"error": f"router: unsupported op {op!r}"})
            return
        try:
            with trace.use_span(rspan):
                if obj.get("stream"):
                    self._generate_stream(state, obj, deadline,
                                          t_arrival)
                else:
                    resp = self._generate(state, obj, deadline,
                                          t_arrival)
                    self._send_client(resp)
        except _ClientGone:
            rspan.end(outcome="client_gone")
            raise
        except _Rejected as e:
            # Structured shed/deadline: NOT a router error — the
            # contract under overload is exactly this reply.
            rspan.end(outcome=e.frame.get("code") or "rejected")
            self._send_client({**e.frame, "done": True})
        except Exception as e:
            state.metrics["errors"] += 1
            rspan.end(outcome="error")
            self._send_client({"error": str(e), "done": True})
        else:
            rspan.end(outcome="ok")

    @staticmethod
    def _stamp_deadline(obj: dict) -> float:
        """Absolute monotonic deadline for this request: the client's
        ``timeout_s`` budget (or the router default), stamped ONCE at
        ingress — every hop, failover attempt, and backend admission below
        derives its remaining budget from this single number."""
        t = obj.get("timeout_s")
        t = DEFAULT_TIMEOUT_S if t is None else float(t)
        if t <= 0:
            raise ValueError(f"timeout_s must be > 0, got {t}")
        return time.monotonic() + t

    @staticmethod
    def _pin_seed(obj: dict) -> dict:
        """Sampled requests without a client seed get a router-assigned one
        BEFORE any backend sees the request, so a failover re-run produces
        the identical stream (position-keyed PRNG: tokens are f(seed,
        position), independent of which replica computes them)."""
        if float(obj.get("temperature", 0.0) or 0.0) > 0.0 \
                and obj.get("seed") is None:
            obj = dict(obj)
            obj["seed"] = random.getrandbits(31)
        return obj

    _FWD_DECODE_KEYS = ("max_new_tokens", "temperature", "top_k", "top_p",
                        "min_p", "repetition_penalty", "presence_penalty",
                        "frequency_penalty", "seed", "logprobs", "json_mode",
                        "regex", "json_schema", "lora", "stop_token",
                        "stream", "token")

    def _route(self, state: RouterState, obj: dict, deadline: float,
               force_bundle: bool = False):
        """Resolve the final leg shared by blocking and streaming paths.
        PD mode runs the (always blocking, failover-wrapped) prefill hop
        here; returns (role, (header, k_bytes, v_bytes), affinity_prompt,
        t_first, pinned) for the leg the caller owns — the caller can
        re-send that payload to any sibling of ``role`` (decode failover;
        ``pinned`` non-None means the payload only works on THAT address
        — a pushed KV stream — and failover is a bundle re-route), the
        affinity prompt (None on cache-less legs) steers cache-aware
        ordering, and ``t_first`` (PD only, else None) is the monotonic
        instant the prefill hop returned: the FIRST TOKEN exists from
        then on, so PD TTFT ends here, not when decode completes.

        KVCache-centric path (default): the decode replica is chosen
        FIRST — transfer-cost-aware: queue depth + estimated KV bytes
        over its measured link rate — and the prefill request carries
        ``push_to``, so KV chunks stream prefill→decode as they compute.
        A prefill that can't push (older build, no transport, early
        connect failure) replies with the bundle and nothing changes."""
        if not force_bundle:    # a fallback re-route is the SAME request
            state.metrics["requests"] += 1
        obj = self._pin_seed(obj)
        if state.pd_mode():
            if not force_bundle:
                state.metrics["pd_requests"] += 1
            # Forward sampling fields: the FIRST token is sampled by the
            # prefill engine — without them it would always be greedy,
            # diverging from unified mode for the identical request.
            pf_req = {"op": "prefill", "prompt": obj["prompt"]}
            for key in ("temperature", "top_k", "top_p", "min_p",
                        "repetition_penalty", "presence_penalty",
                        "frequency_penalty", "seed", "json_mode", "regex",
                        "json_schema", "lora", "stop_token", "token"):
                if key in obj:
                    pf_req[key] = obj[key]
            decode_addr = None
            if state.kv_stream and not force_bundle:
                est = state.est_kv_bytes(len(obj.get("prompt") or ()))
                dcands = state.candidates("decode",
                                          cost=state.kv_cost_fn(est))
                if dcands and not state.pool.is_down(dcands[0]):
                    decode_addr = dcands[0]
                    pf_req["push_to"] = decode_addr
                    pf_req["stream_id"] = f"rtr-{random.getrandbits(48):x}"
            # Cache affinity on the prefill leg: the replica that served
            # this prefix before has it in its radix cache / pool hot set.
            # The prefill leg spends from the SAME deadline the decode leg
            # inherits — a slow prefill shrinks the decode budget.
            _, hdr, kb, vb = state.call("prefill", pf_req,
                                        prompt=obj.get("prompt"),
                                        deadline=deadline)
            hdr.pop("_router_t_dispatch", None)
            t_first = time.monotonic()
            if "error" in hdr:
                raise RuntimeError(f"prefill failed: {hdr}")
            if hdr.get("pushed"):
                # KV already streamed (or streaming) prefill→decode; the
                # router never touched the payload bytes.
                state.metrics["kv_stream_routed"] += 1
                state.note_kv_observed(len(obj.get("prompt") or ()),
                                       int(hdr.get("kv_bytes") or 0))
                state.merge_link_rates(hdr.get("link_rates"))
                fwd = {"op": "decode_stream",
                       "stream_id": hdr["stream_id"]}
                for key in self._FWD_DECODE_KEYS:
                    if key in obj:
                        fwd[key] = obj[key]
                return "decode", (fwd, None, None), None, t_first, \
                    decode_addr
            if hdr.get("pushed") is False:
                # Push failed before the reply (decode peer unreachable):
                # the prefill ran but holds no bundle — re-run it in
                # bundle mode (its radix/pool hot set makes the re-prefill
                # cheap) instead of failing the request.
                state.metrics["kv_stream_fallbacks"] += 1
                pf_req.pop("push_to", None)
                pf_req.pop("stream_id", None)
                _, hdr, kb, vb = state.call("prefill", pf_req,
                                            prompt=obj.get("prompt"),
                                            deadline=deadline)
                hdr.pop("_router_t_dispatch", None)
                t_first = time.monotonic()
                if "error" in hdr:
                    raise RuntimeError(f"prefill failed: {hdr}")
            state.metrics["kv_bytes_routed"] += len(kb or b"") + len(vb or b"")
            state.note_kv_observed(len(obj.get("prompt") or ()),
                                   len(kb or b"") + len(vb or b""))
            fwd = dict(hdr)
            fwd["op"] = "decode_bundle"
            for key in self._FWD_DECODE_KEYS:
                if key in obj:
                    fwd[key] = obj[key]
            # Decode replicas hold no prefix cache — no affinity prompt.
            return "decode", (fwd, kb, vb), None, t_first, None
        return (state.worker_role(), (obj, None, None), obj.get("prompt"),
                None, None)

    def _generate(self, state: RouterState, obj: dict, deadline: float,
                  t_arrival: float) -> dict:
        """Blocking generate. TTFT is anchored at the INGRESS arrival
        stamp: PD requests end it when the prefill hop returns (the first
        token exists then — decode time is NOT first-token time), unified
        requests add the backend-reported ttft to the successful
        attempt's dispatch offset (a failed-over request is charged the
        attempts that preceded it, not just the winner's clock)."""
        pd = state.pd_mode()
        role, payload, aff, t_first, pinned = self._route(state, obj,
                                                          deadline)
        kvb = len(payload[1] or b"") + len(payload[2] or b"")
        fall_back = False
        try:
            addr, resp, _, _ = state.call(role, *payload, prompt=aff,
                                          deadline=deadline, pinned=pinned,
                                          kv_bytes=kvb)
            if pinned is not None and isinstance(resp, dict) \
                    and "error" in resp:
                # The pushed stream's decode leg failed (stream truncated,
                # replica died holding the KV) — recoverable below.
                raise RuntimeError(f"decode_stream failed: {resp}")
        except _Rejected as e:
            # A pinned leg that SHED (overloaded/draining — the replica is
            # healthy, just unwilling) must not surface a 429/503 that a
            # sibling would have absorbed: bundle mode retries the fleet.
            # Deadline rejections stay terminal on any path.
            if pinned is None \
                    or e.frame.get("code") not in RETRYABLE_REJECT_CODES:
                raise
            fall_back = True
        except Exception:
            if pinned is None:
                raise
            fall_back = True
        if fall_back:
            # KVCache-centric leg is gone; the request is not: re-route
            # in bundle mode (pinned seed ⇒ token-exact) and try the
            # decode fleet normally. TTFT honestly re-anchors on the
            # fallback prefill's return.
            state.metrics["kv_stream_fallbacks"] += 1
            role, payload, aff, t_first, _ = self._route(
                state, obj, deadline, force_bundle=True)
            kvb = len(payload[1] or b"") + len(payload[2] or b"")
            addr, resp, _, _ = state.call(role, *payload, prompt=aff,
                                          deadline=deadline, kv_bytes=kvb)
        t_dispatch = resp.pop("_router_t_dispatch", None) \
            if isinstance(resp, dict) else None
        t_done = time.monotonic()
        if pd:
            if "error" in resp:
                raise RuntimeError(f"decode failed: {resp}")
            resp["ttft_s"] = round(t_first - t_arrival, 6)
        elif "error" not in resp and resp.get("ttft_s") is not None \
                and t_dispatch is not None:
            t_first = t_dispatch + float(resp["ttft_s"])
            resp["ttft_s"] = round(t_first - t_arrival, 6)
        else:
            t_first = None
        if "error" not in resp:
            # Ingress-vantage token counters — the production
            # prefill:decode ratio signal the topology policy steers on
            # (topology.router_ingress_signals_fn). Counted on SUCCESS
            # only, both kinds symmetrically: shed/failed requests did
            # no prefill work, and counting them would inflate the
            # ratio toward prefill-heavy exactly when the fleet is
            # failing.
            n_prompt = len(obj.get("prompt") or ())
            state.note_ingress("prefill", float(n_prompt))
            n = len(resp.get("tokens") or ())
            state.note_ingress("decode", float(n))
            if t_first is not None:
                tpot = ((t_done - t_first) / (n - 1)) if n > 1 else 0.0
                state.slo.judge(t_first - t_arrival, tpot,
                                role=role, backend=addr)
        return resp

    def _generate_stream(self, state: RouterState, obj: dict,
                         deadline: float, t_arrival: float) -> None:
        """Streaming generate with mid-stream failover: relay incremental
        token frames from the backend to the client (feeds the SSE front
        end). PD mode streams the decode leg; the prefill leg is one
        blocking hop (its product is the first token + KV).

        If the backend dies mid-stream, the SAME payload is re-sent to a
        sibling (the router still holds the KV bundle / the request), and
        the replayed stream — identical because the seed is pinned — is
        relayed with the already-delivered token prefix skipped. The
        client never sees the failure. A backend that SHEDS the attempt
        (overloaded / draining — always before any token) is routed
        around without eviction; a spent deadline ends the request with a
        structured frame instead of another doomed attempt."""
        role, payload, aff, t_first, pinned = self._route(state, obj,
                                                          deadline)
        akey = PrefixAffinity.key(aff)
        rspan = trace.current()
        kv_bytes = len(payload[1] or b"") + len(payload[2] or b"")
        delivered = 0                  # tokens already relayed to the client
        # SLO timing across attempts: t_first (PD: set by the prefill hop
        # above; unified: the first relayed token frame) survives
        # failover — the replay skips already-delivered tokens, so the
        # client's first token stays the one the clock stopped on.
        timing = {"t_first": t_first}
        last: Optional[Exception] = None
        shed: Optional[dict] = None
        for attempt in range(MAX_ATTEMPTS):
            if deadline - time.monotonic() <= 0:
                state.metrics["deadline_refusals"] += 1
                self._send_client({**_deadline_frame(
                    "deadline spent mid-stream"), "done": True})
                return
            if pinned is not None:
                # The payload is a pushed KV stream — it only exists on
                # ONE decode replica. A failed attempt re-routes in
                # bundle mode below instead of trying siblings.
                cands = [pinned]
            else:
                # Affinity only steers the FIRST attempt: a failover must
                # not re-pin to the remembered (possibly just-dead)
                # backend. KV-carrying legs weigh measured transfer cost.
                cands = (state.candidates_for(role, aff) if attempt == 0
                         else state.candidates(
                             role, cost=state.kv_cost_fn(kv_bytes)))
            if not cands:
                break
            addr = cands[0]
            aspan = rspan.child(obs_names.SPAN_ROUTER_ATTEMPT,
                                backend=addr, attempt=attempt, role=role,
                                stream=True)
            if aspan and kv_bytes:
                aspan.attrs["kv_bytes"] = kv_bytes
            if attempt:
                if not state.charge_retry():
                    aspan.end(outcome="retry_budget_exhausted")
                    break
                state.metrics["retries"] += 1
            attempt_payload = payload
            if aspan:
                attempt_payload = (dict(payload[0], trace=aspan.wire()),
                                   payload[1], payload[2])
            state.pool.acquire(addr)
            try:
                delivered, status, frame = self._relay_attempt(
                    addr, attempt_payload, delivered, deadline,
                    timing=timing)
            finally:
                state.pool.release(addr)
            if status == "done":
                state.pool.ok(addr)
                state.affinity.put(akey, addr)
                if attempt:
                    state.metrics["failovers"] += 1
                aspan.end(outcome="ok", delivered=delivered)
                if frame is None:
                    # Ingress tokens on SUCCESS only, both kinds
                    # symmetrically (the blocking path's rule): a
                    # stream that ultimately fails counts NEITHER side,
                    # so failure storms cannot skew the topology ratio.
                    # ``delivered`` already nets out failover replays.
                    n_prompt = len(obj.get("prompt") or ())
                    state.note_ingress("prefill", float(n_prompt))
                    state.note_ingress("decode", float(delivered))
                # frame is None on a CLEAN stream completion; an
                # application-error passthrough carries its frame and is
                # not a finished request — never judged.
                if timing["t_first"] is not None and frame is None:
                    t_done = time.monotonic()
                    tpot = ((t_done - timing["t_first"]) / (delivered - 1)
                            if delivered > 1 else 0.0)
                    state.slo.judge(timing["t_first"] - t_arrival, tpot,
                                    role=role, backend=addr)
                return
            if status == "rejected":
                # Healthy backend refused the attempt (shed before any
                # token): no eviction; deadline ends the request.
                if frame.get("code") == CODE_DEADLINE:
                    state.pool.ok(addr)
                    aspan.end(outcome=CODE_DEADLINE)
                    self._send_client({**frame, "done": True})
                    return
                if pinned is not None:
                    # The pushed stream is unusable — whether it never
                    # became decodable (kv_stream_failed) or the only
                    # replica holding it SHED the attempt. Retrying the
                    # same pinned address cannot help: re-route in bundle
                    # mode, token-exact (seed pinned, delivered prefix
                    # skipped), and let the fleet absorb it. Sheds still
                    # feed the shed bookkeeping (drain marks, best
                    # retry_after_s should the fallback shed everywhere).
                    code = frame.get("code")
                    if code == CODE_KV_STREAM:
                        state.pool.ok(addr)
                    else:
                        shed = state.note_shed(addr, frame, shed)
                    aspan.end(outcome=code or "rejected")
                    state.metrics["kv_stream_fallbacks"] += 1
                    try:
                        role, payload, aff, _, pinned = self._route(
                            state, obj, deadline, force_bundle=True)
                    except Exception as e:  # noqa: BLE001
                        last = e
                        break
                    kv_bytes = len(payload[1] or b"") \
                        + len(payload[2] or b"")
                    continue
                shed = state.note_shed(addr, frame, shed)
                aspan.end(outcome=frame.get("code") or "rejected")
                continue
            # Backend closed mid-stream without a done frame.
            state.pool.fail(addr)
            aspan.end(outcome="died_mid_stream", delivered=delivered)
            last = RuntimeError(f"{addr} closed mid-stream")
            if pinned is not None:
                # The replica holding the pushed KV died (possibly with
                # tokens already delivered): bundle re-route + replay —
                # the client stream never breaks.
                state.metrics["kv_stream_fallbacks"] += 1
                try:
                    role, payload, aff, _, pinned = self._route(
                        state, obj, deadline, force_bundle=True)
                except Exception as e:  # noqa: BLE001
                    last = e
                    break
                kv_bytes = len(payload[1] or b"") + len(payload[2] or b"")
        if shed is not None:
            state.metrics["sheds_returned"] += 1
            self._send_client({**shed, "done": True})
            return
        state.metrics["errors"] += 1
        self._send_client({
            "error": f"all {role} backends failed mid-stream: {last}",
            "done": True})

    def _send_client(self, frame: dict) -> None:
        try:
            send_msg(self.request, frame)
        except OSError as e:
            raise _ClientGone(str(e)) from e

    def _relay_attempt(self, addr: str, payload, delivered: int,
                       deadline: Optional[float] = None,
                       timing: Optional[dict] = None):
        """One streaming attempt against ``addr``. Relays frames to the
        client, skipping the first ``delivered`` tokens (already sent by a
        previous attempt — deterministic replay makes them identical).
        Returns (new_delivered, status, frame): status "done" with a None
        frame (stream completed cleanly), "done" with the error frame (an
        application error passed through — not a finished request),
        "died" (transport failure — the tokens relayed before it are
        never lost from the count, so the retry skips them instead of
        duplicating), or "rejected" (a structured shed frame, returned
        for the caller's route-around logic instead of being surfaced).
        Client-side send failures raise _ClientGone, which aborts the
        request without charging the backend. ``deadline`` re-arms the
        per-recv timeout from the remaining budget and forwards it to the
        backend. ``timing`` (when given) gets ``t_first`` stamped the
        instant the first NEW token reaches the client — SLO TTFT input."""
        host, port = addr.rsplit(":", 1)
        skip = delivered
        try:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return delivered, "rejected", _deadline_frame(
                        "deadline spent before stream dispatch")
                payload = (dict(payload[0], timeout_s=round(remaining, 3)),
                           payload[1], payload[2])
            with socket.create_connection(
                    (host, int(port)),
                    timeout=min(CONNECT_TIMEOUT_S, remaining)
                    if remaining is not None else CONNECT_TIMEOUT_S) as s:
                # Widen to the stream budget BEFORE sending: the payload
                # can carry a multi-MB KV bundle whose transmission must
                # not be cut by the 5 s connect timeout (that would read
                # as 'died' and evict a healthy backend).
                s.settimeout(min(STREAM_TIMEOUT_S, remaining)
                             if remaining is not None else STREAM_TIMEOUT_S)
                send_msg(s, *payload)
                while True:
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return delivered, "rejected", _deadline_frame(
                                "deadline spent mid-stream")
                        s.settimeout(min(STREAM_TIMEOUT_S, remaining))
                    else:
                        s.settimeout(STREAM_TIMEOUT_S)
                    frame, _, _ = recv_msg(s)
                    if frame is None:
                        return delivered, "died", None
                    if frame.get("keepalive"):
                        # SSE liveness pass-through: forwarded verbatim so
                        # the edge can emit its comment frame, but never
                        # counted as tokens and never re-arming the
                        # deadline — liveness is not progress.
                        self._send_client(frame)
                        continue
                    if "error" in frame:
                        if frame.get("code") in RETRYABLE_REJECT_CODES \
                                or frame.get("code") in (CODE_DEADLINE,
                                                         CODE_KV_STREAM):
                            # Shed at admission (always before any token):
                            # the caller routes around / ends the request
                            # (kv_stream_failed → bundle re-route).
                            return delivered, "rejected", frame
                        # Application error — not a transport failure; the
                        # engine is healthy and answered. Pass through
                        # (frame returned so the caller skips SLO judgment).
                        self._send_client(frame)
                        return delivered, "done", frame
                    tokens = frame.get("tokens") or []
                    drop = min(skip, len(tokens))
                    if drop:
                        skip -= drop
                        frame = dict(frame)
                        frame["tokens"] = tokens[drop:]
                        if "logprobs" in frame:
                            frame["logprobs"] = frame["logprobs"][drop:]
                        tokens = frame["tokens"]
                    if tokens or frame.get("done"):
                        self._send_client(frame)
                        if (tokens and timing is not None
                                and timing.get("t_first") is None):
                            timing["t_first"] = time.monotonic()
                        delivered += len(tokens)
                    if frame.get("done"):
                        return delivered, "done", None
        except (OSError, ConnectionError, json.JSONDecodeError):
            # JSONDecodeError = garbage frame from a version-mismatched or
            # corrupt backend — same class as a transport failure (probe()
            # classifies it identically): fail over, don't surface it.
            return delivered, "died", None


class RouterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def start_prober(state: RouterState, interval_s: float = 0.5) -> threading.Thread:
    """Background re-admission: health-check evicted backends so recovery
    is noticed in ~interval_s instead of waiting out the backoff."""
    def loop():
        while True:
            time.sleep(interval_s)
            try:
                state.pool.probe()
            except Exception:
                pass
    t = threading.Thread(target=loop, daemon=True, name="router-prober")
    t.start()
    return t


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rbg-tpu-router")
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--registry", default=os.environ.get("RBG_REGISTRY_PATH"))
    ap.add_argument("--group", default=os.environ.get("RBG_GROUP_NAME"))
    ap.add_argument("--backends", default="",
                    help='static JSON {"prefill": ["host:port"], ...}')
    ap.add_argument("--auth-token", default="",
                    help="require this bearer token on generate/embed and "
                         "forward it on every backend leg (default: "
                         "$RBG_DATA_TOKEN; empty = open wire)")
    ap.add_argument("--retry-rate", type=float, default=8.0,
                    help="router-wide retry budget: sustained failover "
                         "retries per second (token bucket; shed storms "
                         "must not amplify). 0 disables retries; "
                         "negative = unbounded")
    ap.add_argument("--retry-burst", type=float, default=32.0,
                    help="retry budget burst size (bucket capacity)")
    ap.add_argument("--slo-ttft-s", type=float, default=2.0,
                    help="TTFT target for router-side SLO judgment "
                         "(ingress-anchored; health carries per-role and "
                         "per-backend attainment; 0 disables)")
    ap.add_argument("--slo-tpot-s", type=float, default=0.5,
                    help="per-output-token latency target for router-side "
                         "SLO judgment (0 disables)")
    ap.add_argument("--kv-stream", choices=("auto", "off"), default="auto",
                    help="KVCache-centric PD routing: pick the decode "
                         "replica first (transfer-cost-aware) and have "
                         "prefill push KV chunks to it as they compute; "
                         "'off' keeps the whole-bundle relay path")
    ap.add_argument("--directory",
                    default=os.environ.get("RBG_KV_POOL_ADDR", ""),
                    help="host:port of the cluster prefix directory (the "
                         "kv-pool server hosts it) — prefix affinity can "
                         "then route to ANY replica holding a prefix "
                         "(default: $RBG_KV_POOL_ADDR; empty = local LRU "
                         "only)")
    ap.add_argument("--router-id",
                    default=os.environ.get("RBG_ROUTER_ID", ""),
                    help="stable identity on the router-tier hash ring "
                         "(default: $RBG_ROUTER_ID or router-<port>)")
    ap.add_argument("--drain-wait-s", type=float, default=30.0,
                    help="SIGTERM drain: max seconds to wait for in-flight "
                         "streams to finish before exiting")
    args = ap.parse_args(argv)
    port = int(os.environ.get("RBG_SERVE_PORT")
               or os.environ.get("RBG_PORT_SERVE") or args.port)
    static = json.loads(args.backends) if args.backends else None
    server = RouterServer(("127.0.0.1", port), Handler)
    budget = RetryBudget(rate=None if args.retry_rate < 0 else args.retry_rate,
                         burst=args.retry_burst)
    directory = None
    if args.directory:
        from rbg_tpu.kvtransfer.directory import DirectoryClient
        directory = DirectoryClient(args.directory,
                                    token=args.auth_token or None)
    server.state = RouterState(Registry(args.registry), args.group, static,
                               token=args.auth_token or None,
                               retry_budget=budget,
                               slo_targets=SLOTargets(
                                   ttft_s=args.slo_ttft_s,
                                   tpot_s=args.slo_tpot_s),
                               directory=directory,
                               kv_stream=args.kv_stream != "off",
                               router_id=args.router_id or f"router-{port}")
    from rbg_tpu.obs import timeseries
    timeseries.ensure_started()
    start_prober(server.state)

    # PR-2 drain protocol: SIGTERM flips the admission gate (new requests
    # get the structured draining frame; tier peers take the hash range),
    # in-flight streams finish, then the listener exits cleanly.
    import signal

    def _on_sigterm(signum, frame):
        def drain():
            server.state.begin_drain(wait_s=args.drain_wait_s)
            server.shutdown()
        threading.Thread(target=drain, daemon=True,
                         name="router-drain").start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # non-main thread (embedded use) — drain via begin_drain()
    print(f"router listening on 127.0.0.1:{port} group={args.group}", flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
