"""Host-DRAM KV spill tier — the device pool's second cache level.

Mooncake's core claim (PAPERS.md: "Mooncake: A KVCache-centric
Disaggregated Architecture for LLM Serving") is "more storage for less
computation": a multi-tier KV cache where device HBM is only the top
level. Before this tier existed, a device page-pool eviction threw the
prefix away forever — the next request with the same system prompt paid
full prefill. Now the radix cache's eviction hook copies the evicted
pages into this bounded host-DRAM trie, and an admission hit promotes
them back onto device (a MOVE, not a copy — every cached page lives in
exactly one tier, the ``tier_accounting`` stress invariant).

Backing store: ``engine.kvpool.KVPoolStore`` — the same trie-over-numpy
-pages structure the cluster KV pool uses — extended with placeholder
path nodes (radix eviction is leaf-first, so DEEP pages spill before
shallow ones and the route to them must survive) and LRU-by-hotness
byte-budget eviction.

Accounting contract (``rbg_kvcache_tier_*``):

    spilled_pages_total == promoted_pages_total
                           + evicted_pages_total{tier="host"}
                           + tier_pages{tier="host"}

i.e. every page that ever entered the host tier either went back to
device, was evicted by the byte budget, or is still resident — checked
by ``stress --scenario prefixcache``.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from rbg_tpu.engine.kvpool import KVPoolStore
from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import REGISTRY

TIER_DEVICE = "device"
TIER_HOST = "host"


_PROMOTE_SCATTER = None


def _promote_scatter():
    """One jitted scatter with DONATED pool buffers for every promotion
    (jax.jit re-specializes per shape; the pow2 id bucketing bounds the
    variety). The eager ``.at[].set`` alternative cannot alias a pool
    the engine still references — it materializes a full copy of both
    pool arrays (transient 2× KV HBM) on the admission path per
    promotion. The engine replaces its cache with the result and never
    touches the donated buffers again."""
    global _PROMOTE_SCATTER
    if _PROMOTE_SCATTER is None:
        import jax

        def scatter(kp, vp, ids, k, v):
            return kp.at[:, ids].set(k), vp.at[:, ids].set(v)

        from rbg_tpu.obs.names import PROGRAM_KVTIER_PROMOTE
        scatter.__name__ = PROGRAM_KVTIER_PROMOTE   # jitwatch catalog
        _PROMOTE_SCATTER = jax.jit(scatter, donate_argnums=(0, 1))
    return _PROMOTE_SCATTER


# bucket_fn
def _pow2_bucket(n: int) -> int:
    """Device transfers are padded to power-of-two page counts: a gather
    or scatter of k pages compiles one XLA program PER DISTINCT k, and
    unbucketed spill/promote sizes were measured compiling mid-serving
    on the admission path (the TTFT tail). Page 0 is the engine's
    reserved null page — masked out of every read — so padding ids with
    it is free."""
    b = 1
    while b < n:
        b *= 2
    return b


class HostKVTier:
    """Bounded host-DRAM tier under the device page pool.

    Single-writer: every method is called from the engine loop thread
    (spill inside ``_alloc``'s eviction, promotion inside ``_admit``),
    except ``peek`` which the admission TTFT predictor reads from
    submitter threads — the backing store's lock covers that.
    """

    def __init__(self, page_size: int, max_bytes: int,
                 directory=None, advertise_addr: str = "",
                 slice_id: str = ""):
        self.page_size = page_size
        # The store invalidates directory keys itself on byte-budget
        # eviction (KVPoolStore.put contract) — a directory lookup can
        # never return a host page this tier no longer holds.
        self.store = KVPoolStore(page_size, max_bytes=max_bytes,
                                 directory=directory)
        self.store.owner_backend = advertise_addr
        self.directory = directory
        self.advertise_addr = advertise_addr
        self.slice_id = slice_id
        # Lifetime counters (the accounting identity above; store.stats()
        # carries the live pages/bytes side).
        self.spilled_pages = 0
        self.promoted_pages = 0

    # ---- device -> host (radix eviction hook) ----

    def spill_from_device(self, prefix_tokens: List[int],
                          page_ids: List[int], cache) -> int:
        """Copy an evicted radix leaf's device pages into the host trie.
        ``prefix_tokens`` is the FULL root→leaf prefix; ``page_ids`` are
        the leaf's device pages (its tail ``len(page_ids)`` pages of the
        prefix — shallower pages become placeholder path nodes until
        their own eviction spills them). Returns pages stored."""
        import jax.numpy as jnp

        ps = self.page_size
        from_page = len(prefix_tokens) // ps - len(page_ids)
        if from_page < 0:
            return 0
        t0 = time.perf_counter()
        n = len(page_ids)
        bucket = _pow2_bucket(n)
        ids = jnp.asarray(list(page_ids) + [0] * (bucket - n), jnp.int32)
        k = np.asarray(cache.k_pages[:, ids])[:, :n]
        v = np.asarray(cache.v_pages[:, ids])[:, :n]
        evicted_before = self.store.stats()["evicted_pages"]
        stored = self.store.put(prefix_tokens, k, v,
                                data_from_page=from_page)
        REGISTRY.observe(names.KVC_TIER_SPILL_SECONDS,
                         time.perf_counter() - t0)
        if stored:
            self.spilled_pages += stored
            REGISTRY.inc(names.KVC_TIER_SPILLED_PAGES_TOTAL, float(stored))
        evicted = self.store.stats()["evicted_pages"] - evicted_before
        if evicted:
            REGISTRY.inc(names.KVC_TIER_EVICTED_PAGES_TOTAL, float(evicted),
                         tier=TIER_HOST)
        # Register only what the store ACTUALLY retained: put's own
        # byte-budget eviction may have dropped (and invalidated) the
        # very pages just stored — re-claiming them would hand the
        # router an unbacked host hit exactly under the memory pressure
        # this tier exists to absorb.
        retained = self.store.peek(prefix_tokens, from_page * ps) // ps
        self._register_spill(prefix_tokens, from_page,
                             from_page + retained)
        self.publish_gauges()
        return stored

    # ---- host -> device (admission promotion) ----

    def promote_to_device(self, tokens: List[int], start_tokens: int,
                          alloc_fn, cache,
                          release_fn=None) -> Tuple[int, List[int], object]:
        """Move the host-resident continuation of ``tokens`` past
        ``start_tokens`` (the device radix hit depth) onto device pages.
        ``alloc_fn(n)`` allocates device pages (None = no capacity, even
        after eviction — nothing is touched); ``release_fn(pages)``
        returns surplus pages when the run shrank between peek and take.
        Returns ``(extra_tokens, page_ids, new_cache)``; ``(0, [],
        cache)`` when the host tier has nothing to add."""
        import jax.numpy as jnp

        from rbg_tpu.engine.kvcache import PagedKVCache

        t0 = time.perf_counter()
        # Peek → alloc → bounded take, in that order: taking first and
        # putting back on alloc failure would copy the full run out and
        # back EVERY STEP while a blocked head-of-queue request retries
        # against an exhausted pool — burning serving-loop memcpy and
        # spinning the store's hit/put counters during the exact
        # overload the hierarchy exists to survive. (peek mutates no
        # hotness/LRU state, so a failed attempt leaves no trace.)
        peeked = self.store.peek(tokens, start_tokens)
        if not peeked:
            return 0, [], cache
        pages = alloc_fn(peeked // self.page_size)
        if pages is None:
            return 0, [], cache
        # The alloc may have evicted INTO this store (spill hook), so
        # the run can only have GROWN — cap the take at what we can
        # place; a shrink (host byte-budget eviction) just takes less.
        extra, k, v = self.store.extend(tokens, start_tokens, take=True,
                                        max_tokens=peeked)
        n = extra // self.page_size
        if not extra:
            # Gone between peek and take (byte-budget eviction raced
            # via the alloc's spill) — return the unused device pages.
            if release_fn is not None:
                release_fn(pages)
            return 0, [], cache
        if n < len(pages):
            if release_fn is not None:
                release_fn(pages[n:])
            pages = pages[:n]
        bucket = _pow2_bucket(n)
        if bucket > n:
            # Pad the scatter to the bucket: the extra columns land on
            # the null page (see _pow2_bucket), whose contents no read
            # ever observes.
            zk = np.zeros((k.shape[0], bucket - n) + k.shape[2:], k.dtype)
            zv = np.zeros((v.shape[0], bucket - n) + v.shape[2:], v.dtype)
            k = np.concatenate([k, zk], axis=1)
            v = np.concatenate([v, zv], axis=1)
        ids = jnp.asarray(list(pages) + [0] * (bucket - n), jnp.int32)
        k_pages, v_pages = _promote_scatter()(
            cache.k_pages, cache.v_pages, ids,
            jnp.asarray(k, cache.k_pages.dtype),
            jnp.asarray(v, cache.v_pages.dtype))
        new_cache = PagedKVCache(k_pages=k_pages, v_pages=v_pages)
        REGISTRY.observe(names.KVC_TIER_PROMOTE_SECONDS,
                         time.perf_counter() - t0)
        self.promoted_pages += n
        REGISTRY.inc(names.KVC_TIER_PROMOTED_PAGES_TOTAL, float(n))
        # Tier hit/miss counters are the ENGINE's, on admission success
        # only — a blocked head-of-queue request re-attempts every step
        # and would otherwise inflate the cache panel's rates exactly
        # when the pool-exhaustion it diagnoses is happening.
        # The prefix is device-held again: re-register so the cluster
        # directory's tier tag steers routing cost back to ~free.
        self._register(tokens[:start_tokens + extra], tier=TIER_DEVICE)
        self.publish_gauges()
        return extra, pages, new_cache

    def peek(self, tokens: List[int], start_tokens: int = 0) -> int:
        """Advisory continuation depth (no hotness/LRU mutation) — what
        a request would gain from this tier, for the TTFT predictor."""
        return self.store.peek(tokens, start_tokens)

    def wire_directory(self, directory, advertise_addr: str,
                       slice_id: str = "") -> None:
        """Late directory wiring (the server builds the directory client
        after the engine): both this tier's registrations AND the backing
        store's eviction invalidations must go to the same place."""
        self.directory = directory
        self.store.directory = directory
        # Scope the store's eviction invalidations to THIS replica's
        # claims — shared prefix hashes must not lose siblings' entries.
        self.store.owner_backend = advertise_addr
        self.advertise_addr = advertise_addr
        self.slice_id = slice_id

    # ---- accounting ----

    def _register(self, tokens: List[int], tier: str = TIER_HOST) -> None:
        if self.directory is None or not self.advertise_addr or not tokens:
            return
        try:
            self.directory.register(tokens, self.advertise_addr,
                                    slice_id=self.slice_id, tier=tier)
        except (OSError, RuntimeError, ValueError):
            pass  # the directory is an optimization, never a dependency

    def _register_spill(self, prefix_tokens: List[int], from_page: int,
                        until_page: int) -> None:
        """Per-tier-accurate registration of an evicted leaf's chain:
        the pages BELOW the leaf stay device-resident (radix eviction is
        leaf-first — the parent path survives until its own eviction),
        and only the spilled pages the store RETAINED ([from_page,
        until_page)) are claimed host-tier. Blanket-tagging the whole
        chain host would clobber a live device claim for the shallow
        pages or claim pages the byte budget already dropped."""
        if self.directory is None or not self.advertise_addr:
            return
        from rbg_tpu.kvtransfer.chunks import prefix_keys
        keys = prefix_keys(prefix_tokens, self.page_size)
        try:
            if from_page:
                self.directory.register_keys(
                    keys[:from_page], self.advertise_addr,
                    slice_id=self.slice_id, tier=TIER_DEVICE)
            if until_page > from_page:
                self.directory.register_keys(
                    keys[from_page:until_page], self.advertise_addr,
                    slice_id=self.slice_id, tier=TIER_HOST)
        except (OSError, RuntimeError, ValueError):
            pass  # optimization, never a dependency

    def publish_gauges(self) -> None:
        s = self.store.stats()
        REGISTRY.set_gauge(names.KVC_TIER_PAGES, float(s["pages"]),
                           tier=TIER_HOST)
        REGISTRY.set_gauge(names.KVC_TIER_BYTES, float(s["bytes"]),
                           tier=TIER_HOST)

    def stats(self) -> dict:
        s = self.store.stats()
        s.update(spilled_pages=self.spilled_pages,
                 promoted_pages=self.promoted_pages)
        return s

    def accounting_closes(self) -> bool:
        """The exactly-one-tier identity: every page that ever spilled in
        is either promoted back out, byte-budget evicted, or resident."""
        s = self.store.stats()
        return self.spilled_pages == (self.promoted_pages
                                      + s["evicted_pages"] + s["pages"])
