"""Token sampling — jittable, per-row parameters as arrays (one compiled
sampler serves every batch mix of greedy/temperature/top-k)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample(
    logits: jnp.ndarray,        # [B, V] f32
    key: jax.Array,
    temperature: jnp.ndarray,   # [B] f32; 0 = greedy
    top_k: jnp.ndarray,         # [B] int32; 0 = full vocab
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # top-k mask (per-row k; 0 = disabled)
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]               # [B, V]
    k_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=1)  # [B, 1]
    masked = jnp.where(
        (top_k[:, None] > 0) & (logits < kth), -jnp.inf, logits
    )

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, masked / temp, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)
