"""Token sampling — jittable, per-row parameters as arrays (one compiled
sampler serves every batch mix of greedy/temperature/top-k/top-p/min-p,
with optional repetition/presence/frequency penalties and logprobs).

Design notes (TPU-first):

* **Per-row PRNG streams** — every row samples with its own key,
  ``fold_in(row_key, position)``. Randomness is a pure function of
  (request seed, token position): per-request ``seed`` gives OpenAI-style
  reproducibility, and a decode-state rebuild (batch recomposition,
  preemption resume) replays the identical stream instead of depending on
  how many scan windows ran before it.
* **Gumbel-max** instead of ``jax.random.categorical`` so the per-row keys
  vmap cleanly: ``argmax(logits/T + G)`` with row-keyed Gumbel noise is
  exactly categorical sampling.
* **Masking is value-space** — top-k/top-p/min-p thresholds are computed on
  sorted copies and applied by comparing against the threshold *value*
  (ties at the boundary are kept), which keeps everything O(V log V) sorts
  + elementwise, no scatters, fully fusable by XLA.
* **Penalties are optional state** — they need token-count tensors
  ([B, V]); the engine only threads them through the fused decode scan when
  some request in the batch actually uses penalties, so the common greedy
  path compiles without the arrays entirely.

Semantics follow the de-facto engine conventions (SGLang/vLLM):
repetition_penalty divides positive / multiplies negative logits of any
token seen in prompt or output; presence/frequency penalties subtract from
output-seen tokens; temperature scales before top-k/top-p/min-p; logprobs
report the model distribution after penalties but before temperature.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # avoid -inf NaN traps in (masked - masked) style arithmetic


def apply_penalties(
    logits: jnp.ndarray,        # [B, V] f32
    prompt_mask: jnp.ndarray,   # [B, V] bool — token appears in the prompt
    out_counts: jnp.ndarray,    # [B, V] int32 — occurrences in the output
    rep: jnp.ndarray,           # [B] f32; 1.0 = disabled
    pres: jnp.ndarray,          # [B] f32; 0.0 = disabled
    freq: jnp.ndarray,          # [B] f32; 0.0 = disabled
) -> jnp.ndarray:
    seen = prompt_mask | (out_counts > 0)
    rp = rep[:, None]
    logits = jnp.where(
        seen, jnp.where(logits > 0, logits / rp, logits * rp), logits)
    out_seen = out_counts > 0
    logits = logits - pres[:, None] * out_seen
    logits = logits - freq[:, None] * out_counts.astype(logits.dtype)
    return logits


def _mask_top_k(scaled: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    B, V = scaled.shape
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=1)
    return jnp.where((top_k[:, None] > 0) & (scaled < kth), NEG_INF, scaled)


def _mask_top_p_min_p(scaled: jnp.ndarray, top_p: jnp.ndarray,
                      min_p: jnp.ndarray) -> jnp.ndarray:
    probs = jax.nn.softmax(scaled, axis=-1)
    # top-p: keep the smallest prefix of sorted-desc probs whose exclusive
    # cumulative mass is < top_p; threshold = smallest kept probability.
    sp = jnp.sort(probs, axis=-1)[:, ::-1]
    cum_excl = jnp.cumsum(sp, axis=-1) - sp
    kept = cum_excl < top_p[:, None]
    thresh = jnp.min(jnp.where(kept, sp, jnp.inf), axis=-1, keepdims=True)
    scaled = jnp.where((top_p[:, None] < 1.0) & (probs < thresh),
                       NEG_INF, scaled)
    # min-p: drop tokens whose prob is below min_p * max-prob.
    pmax = jnp.max(probs, axis=-1, keepdims=True)
    scaled = jnp.where((min_p[:, None] > 0.0) & (probs < min_p[:, None] * pmax),
                       NEG_INF, scaled)
    return scaled


def sample(
    logits: jnp.ndarray,        # [B, V] f32
    keys: jax.Array,            # [B] typed PRNG keys — per-row streams
    temperature: jnp.ndarray,   # [B] f32; 0 = greedy
    top_k: jnp.ndarray,         # [B] int32; 0 = full vocab
    top_p: jnp.ndarray,         # [B] f32; 1.0 = disabled
    min_p: jnp.ndarray,         # [B] f32; 0.0 = disabled
    *,
    prompt_mask: Optional[jnp.ndarray] = None,   # [B, V] bool
    out_counts: Optional[jnp.ndarray] = None,    # [B, V] int32
    rep: Optional[jnp.ndarray] = None,           # [B] f32
    pres: Optional[jnp.ndarray] = None,          # [B] f32
    freq: Optional[jnp.ndarray] = None,          # [B] f32
    want_logprobs: bool = False,
    use_top_p_min_p: bool = True,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Returns (token ids [B] int32, logprobs [B] f32 or None).

    Penalty arguments are all-or-nothing: pass every one of prompt_mask /
    out_counts / rep / pres / freq, or none (the caller compiles separate
    variants so the penalty-free path never materializes [B, V] state).
    ``use_top_p_min_p=False`` (static, host-known per batch) compiles out
    the nucleus/min-p softmax+sort — the common greedy/top-k-only batch
    should not pay a second O(V log V) sort per token.
    """
    if prompt_mask is not None:
        logits = apply_penalties(logits, prompt_mask, out_counts,
                                 rep, pres, freq)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    scaled = _mask_top_k(scaled, top_k)
    if use_top_p_min_p:
        scaled = _mask_top_p_min_p(scaled, top_p, min_p)

    # Gumbel-max with per-row keys == per-row categorical.
    noise = jax.vmap(lambda k, row: jax.random.gumbel(k, row.shape,
                                                      row.dtype))(keys, scaled)
    sampled = jnp.argmax(scaled + noise, axis=-1).astype(jnp.int32)
    toks = jnp.where(temperature > 0, sampled, greedy)

    lps = None
    if want_logprobs:
        # Model-distribution logprob of the chosen token (post-penalty,
        # pre-temperature — the OpenAI ``logprobs`` convention).
        full = jax.nn.log_softmax(logits, axis=-1)
        lps = jnp.take_along_axis(full, toks[:, None], axis=-1)[:, 0]
    return toks, lps


@jax.jit
def _row_keys(seed_vals: jnp.ndarray, has_seed: jnp.ndarray,
              rids: jnp.ndarray, fallback_key: jax.Array) -> jax.Array:
    ks = jax.vmap(jax.random.key)(seed_vals)
    kf = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(fallback_key, rids)
    kd = jnp.where(has_seed[:, None], jax.random.key_data(ks),
                   jax.random.key_data(kf))
    return jax.random.wrap_key_data(kd)


def row_keys(seeds, fallback_key: jax.Array, ids) -> jax.Array:
    """Build a [B] key array: rows with a seed get ``key(seed)`` (stable,
    user-reproducible); rows without get ``fold_in(fallback, request id)``
    (distinct streams per request, stable across decode-state rebuilds).
    One fused dispatch — this runs on every decode-state rebuild, inside
    the host scheduling path."""
    # Mask into uint32 — wire seeds are arbitrary ints and NumPy 2.x raises
    # OverflowError on out-of-range conversion (a request must never be able
    # to kill the engine loop thread).
    seed_vals = jnp.asarray(
        [((s if s is not None else 0) & 0xFFFFFFFF) for s in seeds],
        jnp.uint32)
    has_seed = jnp.asarray([s is not None for s in seeds])
    rids = jnp.asarray([int(i) & 0xFFFFFFFF for i in ids], jnp.uint32)
    return _row_keys(seed_vals, has_seed, rids, fallback_key)


def step_keys(keys: jax.Array, pos: jnp.ndarray) -> jax.Array:
    """Per-row key for sampling the token at position ``pos`` (jittable)."""
    return jax.vmap(jax.random.fold_in)(keys, pos)
